"""GCS: the cluster control plane.

Re-design of the reference's gcs_server (reference:
src/ray/gcs/gcs_server/gcs_server.h:79 and the manager classes it owns:
gcs_node_manager, gcs_actor_manager.cc, gcs_placement_group_manager,
gcs_job_manager, gcs_kv_manager, gcs_health_check_manager.h:39,
gcs_task_manager). One asyncio process owns all cluster metadata:

- node table + heartbeat-based failure detection
- actor directory, actor scheduling, restart-on-death (ReconstructActor
  analog, reference: gcs_actor_manager.h:504)
- placement groups with 2-phase prepare/commit reservation across raylets
  (reference: gcs_placement_group_scheduler.cc)
- namespaced KV store (function table, named actors, serve config live here)
- long-poll-free pubsub: subscribers hold an open connection, GCS pushes
  notify frames (reference: src/ray/pubsub/ + pubsub_handler)
- job table and task-event buffer for the state API

Persistence is pluggable-in-principle (in-memory only this round; the
reference's Redis-backed gcs_table_storage is the model for adding it).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
import uuid
from collections import OrderedDict, defaultdict, deque

from ray_tpu._private import rpc
from ray_tpu._private.common import (  # noqa: F401
    _maybe_attach_daemon_profiler,
    NodeInfo,
    add_resources,
    normalize_resources,
    require_fields,
    resources_fit,
    subtract_resources,
    supervised_task,
)
from ray_tpu._private.config import Config

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference: src/ray/protobuf/gcs.proto ActorTableData)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"

# Node drain ladder (reference: autoscaler.proto DrainNode +
# node_manager.cc HandleDrainRaylet). ALIVE nodes schedule normally;
# DRAINING nodes take no new placements while they evacuate; DRAINED
# nodes are safe to terminate and their death is a non-event.
#
# SUSPECT is the suspicion rung of failure detection (reference treats
# connection loss and health as separate signals: gcs_server/
# gcs_health_check_manager vs the node's pubsub channel dying): a lost
# raylet connection marks the node SUSPECT — excluded from NEW placement
# like DRAINING, but nothing is migrated or reconstructed. Only
# heartbeat-timeout expiry promotes SUSPECT -> DEAD; a re-registration
# inside the grace window restores the prior state as a logged non-event.
NODE_ALIVE = "ALIVE"
NODE_SUSPECT = "SUSPECT"
NODE_DRAINING = "DRAINING"
NODE_DRAINED = "DRAINED"
NODE_DEAD = "DEAD"

DRAIN_REASONS = ("preemption", "idle", "manual")

# GCS string rungs → native_policy.NODE_* ints for the actor plane's
# fault-aware ladder view. DRAINED maps onto the draining rung: both
# exclude the node from new native placements without killing it.
_PLANE_NODE_STATES = {
    NODE_ALIVE: 0,
    NODE_SUSPECT: 1,
    NODE_DRAINING: 2,
    NODE_DRAINED: 2,
    NODE_DEAD: 3,
}


def _plane_node_state(state: str) -> int:
    return _PLANE_NODE_STATES.get(state, 1)

# EV_INJECT token the native actor plane stamps on its mirror events
# (arrives in the conn_id slot — see fast_rpc.FastRpcServer.inject_handler).
_ACTOR_PLANE_TOKEN = 1


class _NativeServiceStack:
    """The pump's single native_service slot when two in-pump services
    are chained (actor plane → KV/pubsub). close() tears down front to
    back — the plane holds chain pointers into the KV service, so it
    must die first (both only after the pump loop thread is joined)."""

    def __init__(self, plane, svc):
        self._plane = plane
        self._svc = svc

    def close(self) -> None:
        if self._plane is not None:
            self._plane.close()
            self._plane = None
        if self._svc is not None:
            self._svc.close()
            self._svc = None


# Per-subscriber fanout queue bound. State channels coalesce
# latest-wins per entity, so depth only grows with DISTINCT entities in
# flight; LOGS (no coalesce key) drops oldest past the bound, counted.
_FANOUT_DEPTH = 256


def _fanout_key(channel: str, message):
    """Coalescing key for the bounded per-subscriber fanout queues.

    State channels (NODE/ACTOR/PG/JOB) are level-triggered — subscribers
    react to the LATEST state of an entity, not to every edge — so a
    queue backed up behind a slow subscriber keeps one pending message
    per entity (latest wins). Returns None for channels whose every
    message matters (LOGS) or unrecognized shapes: never coalesced,
    bounded by drop-oldest instead."""
    if not isinstance(message, dict):
        return None
    if channel == "NODE":
        nid = message.get("node_id") or \
            (message.get("node") or {}).get("node_id")
        return ("node", nid) if nid else None
    if channel == "ACTOR":
        aid = message.get("actor_id")
        return ("actor", aid) if aid else None
    if channel == "PG":
        pid = message.get("pg_id")
        return ("pg", pid) if pid else None
    if channel == "JOB":
        jid = message.get("job_id")
        return ("job", jid) if jid else None
    return None


class _SubscriberPump:
    """One supervised sender per subscriber connection (Python fanout
    path). publish() enqueues into the bounded coalescing queue and
    returns immediately; this task alone awaits the subscriber's
    (possibly stalled) socket, so one dead-slow subscriber can no
    longer head-of-line block delivery to every other subscriber on
    the channel. The queue is shared across channels — sends to one
    conn stay ordered."""

    def __init__(self, conn, stats: dict):
        self.conn = conn
        self.stats = stats
        self._q: OrderedDict = OrderedDict()
        self._seq = 0
        self._wake = asyncio.Event()
        self.closed = False
        self._task = supervised_task(self._run(), name="gcs-fanout")

    def push(self, channel: str, message) -> None:
        if self.closed:
            return
        key = _fanout_key(channel, message)
        if key is not None:
            if key in self._q:
                # Re-insert at the tail: the stale pending state for
                # this entity is superseded, ordering follows the
                # newest write.
                del self._q[key]
                self.stats["coalesced"] += 1
        else:
            self._seq += 1
            key = ("#", self._seq)
        self._q[key] = (channel, message)
        while len(self._q) > _FANOUT_DEPTH:
            self._q.popitem(last=False)
            self.stats["dropped"] += 1
        self.stats["enqueued"] += 1
        if len(self._q) > self.stats["max_depth"]:
            self.stats["max_depth"] = len(self._q)
        self._wake.set()

    def close(self) -> None:
        self.closed = True
        self._q.clear()
        self._wake.set()

    async def _run(self) -> None:
        while True:
            await self._wake.wait()
            self._wake.clear()
            if self.closed:
                return
            batch = 0
            while self._q:
                _, (channel, message) = self._q.popitem(last=False)
                try:
                    await self.conn.notify(
                        "Publish", {"channel": channel, "message": message})
                except Exception:
                    self.close()
                    return
                batch += 1
                self.stats["sent"] += 1
            if batch:
                self.stats["batches"] += 1


class GcsServer:
    def __init__(self, config: Config | None = None,
                 persistence_path: str | None = None):
        self.config = config or Config()
        # File-backed metadata persistence (the reference's Redis-backed
        # gcs_table_storage role): tables snapshot here so a restarted GCS
        # resumes with its actor/PG/KV/job state; raylets re-register
        # (reference: NotifyGCSRestart resync, node_manager.cc:1168).
        self.persistence_path = persistence_path
        # Dirty TABLE names awaiting flush (see _MUTATING); a direct
        # mark_dirty() with no argument dirties everything.
        self._dirty: set = set()
        # Native durable table store (src/gcs_store.cc): rows are written
        # through as WAL appends on each flush — only CHANGED rows hit
        # disk (hash-diffed), and a compaction rewrites the snapshot when
        # the WAL outgrows it. Opened in start().
        self._store = None
        self._row_hashes: dict[tuple[str, str], int] = {}
        self._row_sizes: dict[tuple[str, str], int] = {}
        self._persisted_bytes = 0  # total state size for compaction ratio
        self._flush_lock = threading.Lock()
        # Rows touched by in-flight mutating handlers: (table, key)
        # entries recorded AFTER the in-memory mutation, drained by the
        # handler wrapper and written through the WAL BEFORE the RPC
        # reply (per-mutation durability — reference: redis
        # store_client_kv write-through). Shared across concurrent
        # handlers on purpose: flushing another handler's already-applied
        # mutation early is harmless, and each wrapper drains the list
        # after its own handler ran, so its own rows are always covered.
        self._touched: list = []
        self._needs_sync = False  # WAL appends since last fdatasync
        self.nodes: dict[str, NodeInfo] = {}
        self.node_conns: dict[str, rpc.Connection] = {}
        # Per-node GCS->raylet call sessions (see _call_node): the GCS
        # stamps its raylet-bound mutating RPCs so a call replayed across
        # a raylet re-registration executes at most once on the raylet.
        self._node_call_sessions: dict[str, dict] = {}
        self.kv: dict[str, dict[bytes, bytes]] = defaultdict(dict)
        self.actors: dict[str, dict] = {}
        self.named_actors: dict[tuple[str, str], str] = {}
        self.jobs: dict[str, dict] = {}
        self.placement_groups: dict[str, dict] = {}
        self.task_events: deque = deque(maxlen=self.config.task_events_max_buffer)
        self.pending_demand: dict[str, list] = {}
        # Forwarding directory for objects evacuated off drained nodes:
        # oid_hex -> node_id of the copy's new home. Owners consult it
        # (GetObjectRelocations) before falling back to lineage
        # reconstruction when every known location is gone. Bounded:
        # entries beyond the cap age out FIFO.
        self.object_relocations: "dict[str, str]" = {}
        self._relocation_order: deque = deque()
        self._relocation_cap = 65536
        self.subscribers: dict[str, set[rpc.Connection]] = defaultdict(set)
        # Python-fallback fanout: one _SubscriberPump per subscriber
        # conn + shared counters (also fed by the native fanout path's
        # batch counter). Surfaced in GetClusterStatus -> status CLI +
        # /metrics.
        self._fanout_pumps: dict = {}
        self._fanout_stats = {"enqueued": 0, "sent": 0, "coalesced": 0,
                              "dropped": 0, "batches": 0, "max_depth": 0,
                              "native_batches": 0}
        # Streaming recovery (issue 20): True while a restarted GCS is
        # still rehydrating persisted state in the background; flips
        # False when the recovery stream drains. Grants and answers
        # begin within the bounded priority prefix, not after the full
        # table replay.
        self.recovering = False
        self._recovery_backlog: deque = deque()
        self._recovery_stats = {"prefix_rows": 0, "streamed_rows": 0,
                                "prefix_ms": 0.0, "stream_ms": 0.0}
        # Native-pump server when available (src/fastpath.cc): accept,
        # framing, and sends ride the C++ epoll thread; table mutations
        # stay Python above the loop (reference: gcs_server.h:79 runs on
        # a C++ asio loop end-to-end).
        from ray_tpu._private.fast_rpc import make_server

        self._server = make_server(self._handlers(), name="gcs")
        # Native in-pump protocol service (src/gcs_service.cc): when the
        # daemon runs on the fastpath pump, the KV table and pubsub
        # handlers execute entirely in C++ on the loop thread (parse →
        # mutate → WAL write-through → reply) and their frames never
        # reach Python. Installed by _native_service_factory at server
        # start; None on the asyncio fallback.
        self._native_svc = None
        # Native actor plane (src/gcs_actor.cc, RAY_TPU_NATIVE_CONTROL=1):
        # the RegisterActor→CreateActor→ActorReady ladder for the simple
        # hot shape runs on the pump thread; Python mirrors state off
        # EV_INJECT events (_on_native_inject) and keeps every routed
        # shape (named/PG/strategy/resource actors).
        self._actor_plane = None
        # Divergence breaker bookkeeping (issue 19): once the mirror
        # audit trips, owned methods degrade to the Python handlers and
        # stay degraded (re-arming needs an operator restart — the
        # divergence root cause must be understood, not retried).
        self._native_degraded_reason = ""
        self._native_divergence_trips = 0
        self._audit_proto_seen = 0
        # Actor ids whose re-kick _load_state deferred to the native
        # plane's rehydration; re-kicked via Python if install fails.
        self._native_rekick_deferred: list = []
        self._pending_native_kv: list = []   # (key_hex, blob) restore rows
        self._native_appends_seen = 0
        self._native_walfails_seen = 0
        self._health_task: asyncio.Task | None = None
        self._aux_tasks: list = []  # audit + restored-node reaper
        self._actor_seq = 0
        self.start_time = time.time()
        # Native C++ scheduling core (src/scheduler.cc). Mirrors the node
        # table and answers actor/PG placement queries; the pure-Python
        # policies below remain as the fallback when the toolchain is
        # unavailable.
        self.native_sched = None
        try:
            from ray_tpu._private.native_scheduler import ClusterScheduler

            self.native_sched = ClusterScheduler()
        except Exception:
            logger.info("native scheduler unavailable; using Python policies")

    # Mutating RPC -> the persistence tables the HANDLER ITSELF touches.
    # The flush packs + hash-diffs only DIRTY tables, so a KV-heavy
    # cluster does not re-serialize the kv namespace when an actor
    # changed state. Cascades (node death failing over actors, job
    # finish killing actors or GCing kv packages) run through internal
    # paths that call mark_dirty with their OWN tables — listing them
    # here too would force full repacks of the largest tables for
    # handlers that changed nothing in them.
    _MUTATING = {
        "RegisterNode": ("nodes",),
        "NotifyNodeDead": ("nodes",),
        "DrainNode": ("nodes",),
        "DrainComplete": ("nodes", "actors"),
        "KVPut": ("kv",),
        "KVDel": ("kv",),
        "RegisterActor": ("actors", "named_actors"),
        "ActorReady": ("actors",),
        "ReportActorDeath": ("actors", "named_actors"),
        "KillActor": ("actors", "named_actors"),
        "RegisterJob": ("jobs",),
        "FinishJob": ("jobs",),
        "CreatePlacementGroup": ("placement_groups",),
        "RemovePlacementGroup": ("placement_groups",),
    }

    def _handlers(self):
        def wrap(name, fn):
            tables = self._MUTATING.get(name)
            if tables is None:
                return fn

            async def dirty(conn, payload, fn=fn, tables=tables):
                try:
                    return await fn(conn, payload)
                finally:
                    # Write-through BEFORE the reply goes out: rows the
                    # handler _touch()ed hit the WAL now, so a GCS
                    # killed -9 right after the ack replays them.
                    # mark_dirty stays as the hash-diffed catch-all for
                    # mutation sites without a _touch.
                    if self._touched:
                        touched, self._touched = self._touched, []
                        self._persist_touched(touched)
                    self.mark_dirty(tables)

            return dirty

        return {name: wrap(name, fn) for name, fn in {
            "RegisterNode": self.handle_register_node,
            "Heartbeat": self.handle_heartbeat,
            "GetAllNodes": self.handle_get_all_nodes,
            "DrainNode": self.handle_drain_node,
            "DrainComplete": self.handle_drain_complete,
            "GetObjectRelocations": self.handle_get_object_relocations,
            "NotifyNodeDead": self.handle_notify_node_dead,
            "KVPut": self.handle_kv_put,
            "KVGet": self.handle_kv_get,
            "KVDel": self.handle_kv_del,
            "KVKeys": self.handle_kv_keys,
            "KVExists": self.handle_kv_exists,
            "RegisterActor": self.handle_register_actor,
            "ActorReady": self.handle_actor_ready,
            "ReportActorDeath": self.handle_report_actor_death,
            "GetActorInfo": self.handle_get_actor_info,
            "GetNamedActor": self.handle_get_named_actor,
            "ListActors": self.handle_list_actors,
            "KillActor": self.handle_kill_actor,
            "RegisterJob": self.handle_register_job,
            "FinishJob": self.handle_finish_job,
            "ListJobs": self.handle_list_jobs,
            "CreatePlacementGroup": self.handle_create_pg,
            "RemovePlacementGroup": self.handle_remove_pg,
            "GetPlacementGroup": self.handle_get_pg,
            "ListPlacementGroups": self.handle_list_pgs,
            "Subscribe": self.handle_subscribe,
            "Publish": self.handle_publish,
            "AddTaskEvents": self.handle_add_task_events,
            "ListTaskEvents": self.handle_list_task_events,
            "GetClusterStatus": self.handle_get_cluster_status,
            "GetEventLoopStats": self.handle_get_event_loop_stats,
            "GetConfig": self.handle_get_config,
        }.items()}

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        if self.persistence_path:
            from ray_tpu._private.native_gcs_store import GcsTableStore

            self._store = GcsTableStore(self.persistence_path)
            self._load_state()
            from ray_tpu.util import events

            events.configure(os.path.dirname(self.persistence_path), "gcs")
            events.record("INFO", "gcs", "control plane started")
        from ray_tpu._private.fast_rpc import FastRpcServer

        if isinstance(self._server, FastRpcServer):
            self._server.service_factory = self._native_service_factory
        addr = await self._server.start(host, port)
        self._health_task = supervised_task(self._health_check_loop(),
                                            name="gcs-health-loop")
        if self._actor_plane is not None:
            self._aux_tasks.append(supervised_task(
                self._native_audit_loop(), name="gcs-native-audit"))
        if self.persistence_path:
            self._persist_task = supervised_task(self._persist_loop(),
                                                 name="gcs-persist-loop")
            self._aux_tasks.append(supervised_task(
                self._reap_restored_nodes(), name="gcs-reap-restored"))
            if self.recovering:
                # The priority prefix is live; the rest of the persisted
                # state rehydrates behind the serving path.
                self._aux_tasks.append(supervised_task(
                    self._recovery_stream(), name="gcs-recovery-stream"))
        logger.info("GCS listening on %s:%s", *addr)
        return addr

    def _native_service_factory(self, pump):
        """Install the native in-pump services (called by
        FastRpcServer.start between pump creation and listen): the
        KV/pubsub service, and — under RAY_TPU_NATIVE_CONTROL=1 — the
        actor plane chained in FRONT of it (both share the single
        fpump_set_service slot; unowned frames flow plane → KV service
        → Python). Any failure falls back to the Python handlers,
        re-homing kv rows that _load_state stashed for the native
        side."""
        from ray_tpu._private import native_gcs_service

        svc = None
        if native_gcs_service.available():
            try:
                svc = native_gcs_service.GcsNativeService(pump, self._store)
                for key_hex, blob in self._pending_native_kv:
                    ns, k = rpc.unpack(bytes.fromhex(key_hex))
                    svc.kv_load(ns, rpc.pack(k), blob)
                # Hook the pump only once every restored row loaded — a
                # partially-loaded service must never answer frames.
                svc.install()
                self._pending_native_kv = []
                self._native_svc = svc
                logger.info(
                    "native GCS service active (KV + pubsub in-pump)")
            except Exception:
                logger.exception("native GCS service failed to install; "
                                 "Python handles KV/pubsub")
                # The pump hook was never installed (install() is the
                # last step), so the partially-constructed service can be
                # destroyed safely — without this the gsvc_create'd
                # native handle leaks on every fallback.
                if svc is not None:
                    try:
                        svc.close()
                    except Exception:
                        logger.exception("native GCS service close failed")
                svc = None
        if svc is None:
            # Fallback: re-home any rows _load_state stashed for the
            # native side into the Python tables.
            for key_hex, blob in self._pending_native_kv:
                self._restore_kv_row(key_hex, blob)
            self._pending_native_kv = []
        stack = self._install_actor_plane(pump, svc)
        if stack is not None:
            return stack
        return svc

    def _install_actor_plane(self, pump, svc):
        """Chain the native actor plane ahead of the KV service. Returns
        the combined service stack (close() tears down both in order) or
        None when the plane is unavailable / failed to install — in
        which case the KV service's own hook (if any) stays active."""
        from ray_tpu._private import native_actor_plane

        if not native_actor_plane.available():
            self._rekick_deferred_native_actors()
            return None
        plane = None
        try:
            plane = native_actor_plane.GcsActorPlane(
                pump, inject_token=_ACTOR_PLANE_TOKEN)
            if svc is not None:
                plane.chain(svc.frame_addr(), svc.close_addr(), svc._h)
            # Crash rehydration (before install(), so the first frame the
            # plane answers already sees the replayed world): stamp the
            # server incarnation epoch — a replayed request from before
            # the restart carries the old epoch and, with the reply cache
            # gone, must be rejected as stale rather than wrongly deduped
            # or silently re-executed — then replay the persisted node
            # table and every native-owned actor row. Restored nodes are
            # not up (no conn yet); re-registration re-drives parked
            # PENDING actors via the plane's node_up path.
            plane.set_epoch(rpc._server_sessions.epoch)
            for nid, node in self.nodes.items():
                plane.restore_node(nid, _plane_node_state(node.state))
            for aid, a in self._iter_restorable_actors():
                if not a.get("native"):
                    continue
                if a["state"] == ACTOR_ALIVE:
                    pstate = "ALIVE"
                elif a["state"] in (ACTOR_PENDING, ACTOR_RESTARTING):
                    pstate = "PENDING"
                else:
                    continue
                plane.restore_actor(
                    aid, pstate, a.get("restarts", 0),
                    a.get("max_restarts", 0), a.get("node_id") or "",
                    rpc.pack(a["spec"]))
            self._native_rekick_deferred = []
            # install() replaces the KV service's pump hook — the plane
            # forwards everything it doesn't own down the chain, so
            # this must be the LAST step (a half-wired plane must never
            # answer frames).
            plane.install()
            self._server.inject_handler = self._on_native_inject
            self._actor_plane = plane
            logger.info("native control plane active (actor ladder "
                        "in-pump, graftgen validators + reply cache)")
            return _NativeServiceStack(plane, svc)
        except Exception:
            logger.exception("native actor plane failed to install; "
                             "Python handles the actor ladder")
            if plane is not None:
                try:
                    plane.close()
                except Exception:
                    logger.exception("native actor plane close failed")
            self._rekick_deferred_native_actors()
            return None

    def _iter_restorable_actors(self):
        """Actor rows for the plane's pre-install rehydration: the
        prefix-applied tables plus rows still staged on the recovery
        backlog (decoded at load time). The plane must see the full
        replayed world before install(); the Python mirror of the
        backlog rows catches up via _recovery_stream."""
        yield from self.actors.items()
        for table, key_hex, _blob, row in self._recovery_backlog:
            if table == "actors" and row is not None:
                yield bytes.fromhex(key_hex).decode(), row

    def _rekick_deferred_native_actors(self) -> None:
        """_load_state deferred these re-kicks to the plane's
        rehydration; with no plane, Python's scheduler owns them."""
        deferred, self._native_rekick_deferred = (
            self._native_rekick_deferred, [])
        for actor_id in deferred:
            a = self.actors.get(actor_id)
            if a is None or a["state"] not in (ACTOR_PENDING,
                                               ACTOR_RESTARTING):
                continue
            a.pop("native", None)
            asyncio.get_event_loop().call_later(
                1.0, lambda aid=actor_id: supervised_task(
                    self._schedule_actor(aid)))

    # ---------- native actor plane mirror ----------
    # The plane decides on the pump thread and narrates every decision
    # through EV_INJECT ([event, payload] msgpack bodies); Python applies
    # them to the authoritative tables in arrival order. Mirror handlers
    # mutate state before their first await, so interleaving with RPC
    # handlers cannot reorder the per-actor ladder.

    def _on_native_inject(self, token, body):
        if token != _ACTOR_PLANE_TOKEN:
            return
        try:
            event, payload = rpc.unpack(body)
        except Exception:
            logger.exception("native actor plane: bad inject event")
            return
        supervised_task(self._apply_native_actor_event(event, payload),
                        name=f"native-actor-{event}")

    async def _apply_native_actor_event(self, event: str, payload):
        if event == "registered":
            # payload is the original RegisterActor payload (the plane
            # only owns nameless, strategy-less, resource-less actors).
            for stamp in (rpc._SID_KEY, rpc._RSEQ_KEY, rpc._ACK_KEY):
                payload.pop(stamp, None)
            actor_id = payload["actor_id"]
            self.actors[actor_id] = {
                "actor_id": actor_id,
                "job_id": payload.get("job_id", ""),
                "name": "",
                "namespace": payload.get("namespace") or "default",
                "class_name": payload.get("class_name", ""),
                "state": ACTOR_PENDING,
                "spec": payload["spec"],
                "resources": {},
                "max_restarts": payload.get("max_restarts", 0),
                "restarts": 0,
                "node_id": None,
                "address": None,
                "detached": payload.get("detached", False),
                "owner": payload.get("owner"),
                "death_cause": None,
                "strategy": None,
                "placement_group": "",
                "pg_bundle_index": -1,
                "native": True,
            }
            self.mark_dirty(("actors",))
            self._record_task_event(
                self._creation_task_id(actor_id, payload["spec"]),
                payload.get("class_name", ""), "CREATE_REGISTERED",
                job_id=payload.get("job_id", ""), actor_id=actor_id)
            return
        actor_id = payload.get("actor_id", "")
        a = self.actors.get(actor_id)
        if a is None:
            return
        if event == "scheduled":
            node_id = payload["node_id"]
            a["node_id"] = node_id
            self.mark_dirty(("actors",))
            # Same transient placement debit as _schedule_actor: the
            # plane charges CPU:1 so bursts fan out; the next heartbeat
            # restores ground truth.
            node = self.nodes.get(node_id)
            if node is not None:
                subtract_resources(node.available_resources, {"CPU": 1.0})
            if self.native_sched is not None:
                self.native_sched.debit_node(node_id, {"CPU": 1.0})
            self._record_task_event(
                self._creation_task_id(actor_id, a["spec"]),
                a["class_name"], "CREATE_SCHEDULED",
                job_id=a.get("job_id", ""), actor_id=actor_id,
                target_node=node_id)
        elif event == "ready":
            a["state"] = ACTOR_ALIVE
            a["address"] = payload.get("address")
            a["restarts"] = payload.get("restarts", a["restarts"])
            self.mark_dirty(("actors",))
            self._record_task_event(
                self._creation_task_id(actor_id, a["spec"]),
                a["class_name"], "CREATE_READY",
                job_id=a.get("job_id", ""), actor_id=actor_id)
            await self.publish("ACTOR", {
                "actor_id": actor_id, "state": ACTOR_ALIVE,
                "address": a["address"], "restarts": a["restarts"]})
        elif event == "restarting":
            a["restarts"] = payload.get("restarts", a["restarts"] + 1)
            a["state"] = ACTOR_RESTARTING
            a["address"] = None
            self.mark_dirty(("actors",))
            await self.publish("ACTOR", {
                "actor_id": actor_id, "state": ACTOR_RESTARTING,
                "reason": payload.get("reason", "")})
        elif event == "dead":
            a.pop("native", None)
            a["state"] = ACTOR_DEAD
            a["address"] = None
            a["death_cause"] = payload.get("reason", "")
            self.mark_dirty(("actors",))
            from ray_tpu.util import events

            events.record("WARNING", "gcs", "actor dead",
                          actor_id=actor_id)
            await self.publish("ACTOR", {
                "actor_id": actor_id, "state": ACTOR_DEAD,
                "reason": payload.get("reason", "")})
        elif event == "orphaned":
            # The plane found no feasible node and handed the actor back
            # for good (its record is gone; the mirror keeps the restart
            # count). Python's scheduler takes over with its retry loop.
            a.pop("native", None)
            supervised_task(self._schedule_actor(actor_id))

    def _restore_kv_row(self, key_hex: str, blob: bytes) -> None:
        """Restore one persisted kv row into the Python tables. The
        decoded key type (str vs bytes) is preserved: a str-keyed row
        written by the native service must answer a str-keyed KVGet
        after a fallback restart (the live tables keep the two distinct,
        exactly like the native service's raw-encoding identity)."""
        ns, k = rpc.unpack(bytes.fromhex(key_hex))
        self.kv[ns][k] = rpc.unpack(blob)
        self._row_hashes[("kv", key_hex)] = hash(blob)
        self._row_sizes[("kv", key_hex)] = len(blob)

    async def stop(self):
        self._native_svc = None  # server stop destroys the service stack
        self._actor_plane = None
        for pump in list(self._fanout_pumps.values()):
            pump.close()
        self._fanout_pumps.clear()
        if self._health_task:
            self._health_task.cancel()
        if getattr(self, "_persist_task", None):
            self._persist_task.cancel()
        for t in self._aux_tasks:
            t.cancel()
        self._aux_tasks = []
        # Server (and its pump loop thread, which may be running native
        # KV write-throughs) must be fully stopped BEFORE the store is
        # flushed and closed.
        await self._server.stop()
        if self._store is not None:
            # Flush acknowledged mutations from the last <0.5s window,
            # then compact so restart replays a snapshot, not a long WAL.
            tables = set()
            try:
                if self._dirty:
                    tables, self._dirty = self._dirty, set()
                    self._flush_rows(self._table_rows(only=tables), tables)
                self._store.compact()
            except Exception:
                self.mark_dirty(tables)
                logger.exception("final GCS persistence flush failed")
            self._store.close()

    # ---------- persistence ----------
    # Tables persist as (namespace, key) -> msgpack'd row in the native
    # WAL store (src/gcs_store.cc — the reference's gcs_table_storage /
    # store_client role). Flushes are row-INCREMENTAL: rows are packed
    # and hash-diffed against the last flush, so disk writes are O(rows
    # changed), not O(cluster state), and a restart replays snapshot +
    # WAL. Store keys are hex (binary-safe for user internal_kv keys).

    _ALL_TABLES = ("kv", "actors", "named_actors", "jobs",
                   "placement_groups", "nodes")

    def mark_dirty(self, tables=None):
        self._dirty.update(tables if tables is not None else
                           self._ALL_TABLES)

    def _touch(self, table: str, key) -> None:
        """Record one mutated row for pre-reply write-through. Call
        AFTER the in-memory mutation, with the live-table key:
        kv=(ns, key_bytes), actors/jobs/placement_groups=str id,
        named_actors=(name, namespace), nodes=node_id."""
        if self._store is not None:
            self._touched.append((table, key))

    def _pack_row(self, table: str, key):
        """(store_key_hex, row_bytes | None) for one live-table row —
        None when the key is gone (row delete). Mirrors _table_rows."""
        if table == "kv":
            ns, k = key
            v = self.kv.get(ns, {}).get(k)
            return rpc.pack([ns, k]).hex(), (None if v is None
                                             else rpc.pack(v))
        if table == "actors":
            a = self.actors.get(key)
            if a is not None:
                a = dict(a)
                if isinstance(a.get("dead_worker_ids"), set):
                    a["dead_worker_ids"] = sorted(a["dead_worker_ids"])
            return key.encode().hex(), None if a is None else rpc.pack(a)
        if table == "named_actors":
            v = self.named_actors.get(key)
            return (rpc.pack(list(key)).hex(),
                    None if v is None else rpc.pack(v))
        if table == "jobs":
            j = self.jobs.get(key)
            return key.encode().hex(), None if j is None else rpc.pack(j)
        if table == "placement_groups":
            pg = self.placement_groups.get(key)
            return key.encode().hex(), None if pg is None else rpc.pack(pg)
        if table == "nodes":
            n = self.nodes.get(key)
            return (key.encode().hex(),
                    None if n is None else rpc.pack(n.to_wire()))
        raise ValueError(f"unknown persistence table {table!r}")

    def _persist_touched(self, touched: list) -> None:
        """Write touched rows through the WAL synchronously (before the
        RPC reply). Failures fall back to the debounced flush via
        mark_dirty."""
        with self._flush_lock:
            for table, key in touched:
                try:
                    key_hex, blob = self._pack_row(table, key)
                except Exception:
                    logger.exception("write-through pack failed (%s)", table)
                    self.mark_dirty((table,))
                    continue
                if blob is None:
                    if (table, key_hex) in self._row_hashes:
                        if self._store.delete(table, key_hex):
                            del self._row_hashes[(table, key_hex)]
                            self._persisted_bytes -= \
                                self._row_sizes.pop((table, key_hex), 0)
                            self._needs_sync = True
                        else:
                            self.mark_dirty((table,))
                    continue
                h = hash(blob)
                if self._row_hashes.get((table, key_hex)) == h:
                    continue  # unchanged (idempotent re-touch)
                if self._store.put(table, key_hex, blob):
                    self._row_hashes[(table, key_hex)] = h
                    self._persisted_bytes += (
                        len(blob) - self._row_sizes.get((table, key_hex), 0))
                    self._row_sizes[(table, key_hex)] = len(blob)
                    self._needs_sync = True
                else:
                    self._row_hashes.pop((table, key_hex), None)
                    self.mark_dirty((table,))

    def _table_rows(self, only=None) -> dict:
        """Pack live tables into {(namespace, hex_key): row_bytes}.
        `only` limits packing to the named (dirty) tables — a KV-heavy
        cluster must not re-serialize every kv row because one actor
        changed state."""
        want = set(only) if only is not None else set(self._ALL_TABLES)
        rows: dict[tuple[str, str], bytes] = {}
        if "kv" in want:
            for ns, table in self.kv.items():
                for k, v in table.items():
                    rows[("kv", rpc.pack([ns, k]).hex())] = rpc.pack(v)
        if "actors" in want:
            for aid, a in self.actors.items():
                a = dict(a)
                if isinstance(a.get("dead_worker_ids"), set):
                    a["dead_worker_ids"] = sorted(a["dead_worker_ids"])
                rows[("actors", aid.encode().hex())] = rpc.pack(a)
        if "named_actors" in want:
            for k, v in self.named_actors.items():
                rows[("named_actors", rpc.pack(list(k)).hex())] = rpc.pack(v)
        if "jobs" in want:
            for jid, j in self.jobs.items():
                rows[("jobs", jid.encode().hex())] = rpc.pack(j)
        if "placement_groups" in want:
            for pgid, pg in self.placement_groups.items():
                rows[("placement_groups", pgid.encode().hex())] = rpc.pack(pg)
        if "nodes" in want:
            for n in self.nodes.values():
                rows[("nodes", n.node_id.encode().hex())] = \
                    rpc.pack(n.to_wire())
        return rows

    def _flush_rows(self, rows: dict, tables=None) -> int:
        """Write changed rows through to the native store; delete
        vanished rows (sweep limited to the flushed `tables` — rows of
        unflushed tables are absent from `rows` but not deleted).
        Returns the number of rows touched. Serialized by a lock:
        stop()'s final flush may overlap a cancelled-but-still-running
        to_thread flush, and the two must not race the hash map. A
        failed WAL append (disk full) leaves the row unhashed so a
        later flush retries it."""
        swept = set(tables) if tables is not None else set(self._ALL_TABLES)
        with self._flush_lock:
            touched = 0
            failed = 0
            for (ns, key), blob in rows.items():
                h = hash(blob)
                if self._row_hashes.get((ns, key)) != h:
                    if self._store.put(ns, key, blob):
                        self._row_hashes[(ns, key)] = h
                        self._persisted_bytes += (
                            len(blob) - self._row_sizes.get((ns, key), 0))
                        self._row_sizes[(ns, key)] = len(blob)
                    else:
                        self._row_hashes.pop((ns, key), None)
                        failed += 1
                        self.mark_dirty((ns,))  # retry next window
                    touched += 1
            for (ns, key) in list(self._row_hashes):
                if ns in swept and (ns, key) not in rows:
                    if self._store.delete(ns, key):
                        del self._row_hashes[(ns, key)]
                        self._persisted_bytes -= \
                            self._row_sizes.pop((ns, key), 0)
                    else:
                        failed += 1
                        self.mark_dirty((ns,))
                    touched += 1
            if failed:
                logger.error("GCS persistence: %d row writes failed "
                             "(disk full?); will retry", failed)
            return touched

    def _load_state(self):
        """Restore the PRIORITY PREFIX of persisted state synchronously
        — the bounded set a restarted control plane needs to answer and
        grant correctly from its first frame — and stage everything
        else on `_recovery_backlog` for the background recovery stream
        (issue 20: recovery is a stream, not a snapshot).

        Prefix, in priority order: every node row with live nodes
        first (placement and heartbeat replies need the full width
        view — bounded by cluster size, not workload), then in-flight
        actor creations (PENDING/RESTARTING rows, whose re-kicks must
        not be lost). The rest — the workload-proportional bulk:
        settled actors, named-actor index, jobs, placement groups —
        rehydrates incrementally in _recovery_stream; reads that race
        the stream fault their rows in via _recovery_faultin."""
        if self._store.num_rows() == 0:
            # A file AT the bare prefix is the pre-WAL single-snapshot
            # format (replaced this round); it is not migrated — surface
            # that instead of silently starting fresh over it.
            if os.path.exists(self.persistence_path):
                logger.warning(
                    "found legacy single-file GCS snapshot at %s; the WAL "
                    "store does not migrate it — starting fresh",
                    self.persistence_path)
            return  # first start of this session
        t0 = time.monotonic()
        native_kv = self._native_kv_planned()
        for key_hex, blob in self._store.scan("kv"):
            if native_kv:
                # The native service will own these rows (it re-writes
                # them through the WAL itself); keeping them out of
                # _row_hashes keeps the Python flush sweep away from
                # the kv namespace.
                self._pending_native_kv.append((key_hex, blob))
            else:
                self._restore_kv_row(key_hex, blob)
            self._persisted_bytes += len(blob)
        # Priority 1: the node table, live rungs first.
        node_rows = [(key_hex, blob, rpc.unpack(blob))
                     for key_hex, blob in self._store.scan("nodes")]
        node_rows.sort(key=lambda r: 0 if r[2].get("state", "ALIVE") in (
            NODE_ALIVE, NODE_SUSPECT, NODE_DRAINING) else 1)
        for key_hex, blob, w in node_rows:
            info = NodeInfo(
                node_id=w["node_id"], host=w["host"],
                raylet_port=w["raylet_port"],
                total_resources=w["total_resources"],
                available_resources=w["available_resources"],
                labels=w.get("labels") or {}, store_path=w.get("store_path", ""),
                is_head=w.get("is_head", False),
                transfer_port=w.get("transfer_port", 0),
                state=w.get("state", "ALIVE"),
                drain_reason=w.get("drain_reason", ""),
                drain_deadline_s=w.get("drain_deadline_s", 0.0),
                drain_stats=w.get("drain_stats") or {})
            # Nodes come back when their raylet re-registers; stale-alive
            # entries would mislead placement.
            info.alive = False
            self.nodes[info.node_id] = info
            self._row_hashes[("nodes", key_hex)] = hash(blob)
            self._row_sizes[("nodes", key_hex)] = len(blob)
            self._persisted_bytes += len(blob)
        self._restored_unregistered = {
            nid for nid, n in self.nodes.items() if not n.alive}
        # Priority 2: in-flight actor creations. Re-kick scheduling that
        # died with the previous process. Native-owned actors are
        # deferred: the plane's rehydration (restore_actor + re-drive on
        # node re-registration) replays them with at-most-once
        # semantics; a Python re-kick here would race it and fork the
        # creation. If the plane then fails to install,
        # _rekick_deferred_native_actors hands them back.
        native_planned = self._native_actor_planned()
        backlog: deque = deque()
        prefix_rows = len(node_rows)
        for key_hex, blob in self._store.scan("actors"):
            a = rpc.unpack(blob)
            a["dead_worker_ids"] = set(a.get("dead_worker_ids", ()))
            if a["state"] not in (ACTOR_PENDING, ACTOR_RESTARTING):
                backlog.append(("actors", key_hex, blob, a))
                continue
            aid = bytes.fromhex(key_hex).decode()
            self.actors[aid] = a
            self._row_hashes[("actors", key_hex)] = hash(blob)
            self._row_sizes[("actors", key_hex)] = len(blob)
            self._persisted_bytes += len(blob)
            prefix_rows += 1
            if native_planned and a.get("native"):
                self._native_rekick_deferred.append(aid)
                continue
            asyncio.get_event_loop().call_later(
                1.0, lambda aid=aid: supervised_task(
                    self._schedule_actor(aid)))
        # The rest rides the stream (PG_PENDING re-kicks fire as their
        # rows apply).
        for table in ("named_actors", "jobs", "placement_groups"):
            for key_hex, blob in self._store.scan(table):
                backlog.append((table, key_hex, blob, None))
        self._recovery_backlog = backlog
        self.recovering = bool(backlog)
        self._recovery_stats["prefix_rows"] = prefix_rows
        self._recovery_stats["prefix_ms"] = (time.monotonic() - t0) * 1e3
        logger.info("GCS recovery prefix loaded from %s in %.1fms "
                    "(%d nodes, %d pending actors, %d kv ns; %d rows "
                    "streaming)", self.persistence_path,
                    self._recovery_stats["prefix_ms"], len(self.nodes),
                    len(self.actors), len(self.kv), len(backlog))

    def _apply_recovery_row(self, table, key_hex, blob, row) -> None:
        """Apply one backlog row to the live tables. A key the running
        workload already (re)created wins over the snapshot — the
        stream only fills gaps, it never rolls live state back."""
        if table == "actors":
            aid = bytes.fromhex(key_hex).decode()
            if aid in self.actors:
                return
            self.actors[aid] = row
        elif table == "named_actors":
            key = tuple(rpc.unpack(bytes.fromhex(key_hex)))
            if key in self.named_actors:
                return
            self.named_actors[key] = rpc.unpack(blob)
        elif table == "jobs":
            jid = bytes.fromhex(key_hex).decode()
            if jid in self.jobs:
                return
            self.jobs[jid] = rpc.unpack(blob)
        elif table == "placement_groups":
            pid = bytes.fromhex(key_hex).decode()
            if pid in self.placement_groups:
                return
            pg = rpc.unpack(blob)
            self.placement_groups[pid] = pg
            if pg["state"] == PG_PENDING:
                asyncio.get_event_loop().call_later(
                    1.0, lambda p=pid: supervised_task(
                        self._schedule_pg(p)))
        self._row_hashes[(table, key_hex)] = hash(blob)
        self._row_sizes[(table, key_hex)] = len(blob)
        self._persisted_bytes += len(blob)

    async def _recovery_stream(self):
        """Drain the recovery backlog incrementally, yielding to the
        loop between chunks so answering and granting never wait on the
        full-table replay. Flips `recovering` off when dry."""
        t0 = time.monotonic()
        applied = 0
        try:
            while self._recovery_backlog:
                self._apply_recovery_row(*self._recovery_backlog.popleft())
                applied += 1
                if applied % 256 == 0:
                    await asyncio.sleep(0)
        finally:
            self.recovering = False
            self._recovery_stats["streamed_rows"] += applied
            self._recovery_stats["stream_ms"] = \
                (time.monotonic() - t0) * 1e3
            logger.info("GCS recovery stream drained (%d rows in %.1fms)",
                        applied, self._recovery_stats["stream_ms"])

    def _recovery_faultin(self, pred) -> None:
        """Synchronously apply (and drop) backlog rows matching pred —
        the read-through for lookups racing the recovery stream. O(n)
        over the remaining backlog, only while `recovering`."""
        if not self.recovering or not self._recovery_backlog:
            return
        keep: deque = deque()
        faulted = 0
        while self._recovery_backlog:
            item = self._recovery_backlog.popleft()
            if pred(item):
                self._apply_recovery_row(*item)
                faulted += 1
            else:
                keep.append(item)
        self._recovery_backlog = keep
        self._recovery_stats["streamed_rows"] += faulted

    async def _reap_restored_nodes(self):
        """Nodes restored from the snapshot that never re-registered are
        dead: fail over their actors (restart elsewhere or mark DEAD) the
        same way a live death would."""
        grace = max(10.0, self.config.health_check_period_s
                    * self.config.num_heartbeats_timeout * 3)
        await asyncio.sleep(grace)
        for nid in list(getattr(self, "_restored_unregistered", ())):
            node = self.nodes.get(nid)
            if node is None or node.alive:
                continue
            logger.warning("restored node %s never re-registered; failing "
                           "over its actors", nid[:8])
            for actor_id, a in list(self.actors.items()):
                if a.get("node_id") == nid and a["state"] in (
                        ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                    await self._on_actor_worker_death(
                        actor_id, f"node {nid[:8]} lost across GCS restart")
            self.mark_dirty(("actors", "named_actors"))

    async def _persist_loop(self):
        while True:
            await asyncio.sleep(0.5)
            if self._native_svc is not None:
                # Native KV mutations append to the WAL on the pump
                # thread; fold them into the same batched-fdatasync
                # window, and surface disk-full failures.
                _, appends, fails = self._native_svc.counters()
                if appends != self._native_appends_seen:
                    self._native_appends_seen = appends
                    self._needs_sync = True
                if fails != self._native_walfails_seen:
                    self._native_walfails_seen = fails
                    logger.error(
                        "native GCS service: %d WAL appends failed "
                        "(disk full?)", fails)
            if self._needs_sync:
                # Batched fdatasync: write-through already made every
                # acknowledged mutation process-crash durable; this
                # bounds OS-crash exposure to one window (redis
                # appendfsync-everysec semantics).
                self._needs_sync = False
                await asyncio.to_thread(self._store.sync)
            if not self._dirty:
                # Compaction must not be gated on Python-side dirtiness:
                # a kv-churn workload handled entirely by the native
                # service never dirties a Python table, yet its WAL
                # appends still need folding into the snapshot.
                if self._store.wal_bytes() > max(
                        1 << 20, 4 * self._persisted_bytes):
                    await asyncio.to_thread(self._store.compact)
                continue
            tables, self._dirty = self._dirty, set()
            try:
                # Pack DIRTY tables' rows ON the loop (consistent view —
                # same role the old deepcopy played, at a cost bounded by
                # what actually changed); the diff + WAL writes run
                # off-loop (the store is thread-safe).
                rows = self._table_rows(only=tables)
                await asyncio.to_thread(self._flush_rows, rows, tables)
                # Compact once the WAL outgrows the TOTAL persisted
                # state (not this flush's dirty subset — that would
                # trigger full-snapshot rewrites on every small change).
                if self._store.wal_bytes() > max(
                        1 << 20, 4 * self._persisted_bytes):
                    await asyncio.to_thread(self._store.compact)
            except Exception:
                # Re-dirty the swapped tables: with per-table dirtying,
                # an unrelated later mutation would no longer re-flush
                # the rows this failed window carried.
                self.mark_dirty(tables)
                logger.exception("GCS persistence write failed")

    # ---------- pubsub ----------

    async def handle_subscribe(self, conn, payload):
        require_fields(payload, "channels", method="handle_subscribe")
        for channel in payload["channels"]:
            self.subscribers[channel].add(conn)
            conn.on_close(lambda ch=channel: self.subscribers[ch].discard(conn))
        return {"ok": True}

    async def handle_publish(self, conn, payload):
        require_fields(payload, "channel", "message", method="handle_publish")
        await self.publish(payload["channel"], payload["message"])
        return {"ok": True}

    def _native_kv_planned(self) -> bool:
        from ray_tpu._private.fast_rpc import FastRpcServer

        if not isinstance(self._server, FastRpcServer):
            return False
        from ray_tpu._private import native_gcs_service

        return native_gcs_service.available()

    def _native_actor_planned(self) -> bool:
        from ray_tpu._private.fast_rpc import FastRpcServer

        if not isinstance(self._server, FastRpcServer):
            return False
        from ray_tpu._private import native_actor_plane

        return native_actor_plane.available()

    async def publish(self, channel: str, message):
        if self._native_svc is not None:
            # One ctypes call, N native sends — and no packing at all
            # when nobody subscribed (the common case for LOGS).
            if self._native_svc.sub_count(channel):
                self._native_svc.fanout(channel, rpc.pack(
                    [rpc.MSG_NOTIFY, 0, "Publish",
                     {"channel": channel, "message": message}]))
                self._fanout_stats["native_batches"] += 1
            return
        # Python fallback: enqueue-and-return into per-subscriber
        # supervised sender pumps. publish() itself never awaits a
        # subscriber socket — a stalled conn backs up only its own
        # bounded queue (coalesced latest-wins per entity on state
        # channels, drop-oldest-counted otherwise).
        dead = []
        for conn in list(self.subscribers.get(channel, ())):
            if getattr(conn, "closed", False):
                dead.append(conn)
                continue
            pump = self._fanout_pumps.get(conn)
            if pump is None or pump.closed:
                pump = _SubscriberPump(conn, self._fanout_stats)
                self._fanout_pumps[conn] = pump
                conn.on_close(lambda c=conn: self._drop_fanout_pump(c))
            pump.push(channel, message)
        for conn in dead:
            self.subscribers[channel].discard(conn)
            self._drop_fanout_pump(conn)

    def _drop_fanout_pump(self, conn) -> None:
        pump = self._fanout_pumps.pop(conn, None)
        if pump is not None:
            pump.close()

    # ---------- nodes ----------

    async def handle_register_node(self, conn, payload):
        require_fields(payload, "host", "node_id", "raylet_port",
                       "total_resources", method="handle_register_node")
        node_id = payload["node_id"]
        existing = self.nodes.get(node_id)
        if existing is not None and existing.alive:
            # Re-registration of a LIVE node: the raylet's session
            # reconnected after a socket flap (or a half-open link the
            # GCS never noticed). Re-bind the connection and restore the
            # pre-suspect state — a fresh NodeInfo here would wipe drain
            # progress and heartbeat history, the flap-resurrect hole.
            return await self._handle_node_reregister(conn, existing, payload)
        info = NodeInfo(
            node_id=node_id,
            host=payload["host"],
            raylet_port=payload["raylet_port"],
            total_resources=normalize_resources(payload["total_resources"]),
            available_resources=normalize_resources(payload["total_resources"]),
            labels=payload.get("labels") or {},
            store_path=payload.get("store_path", ""),
            is_head=payload.get("is_head", False),
            transfer_port=payload.get("transfer_port", 0),
        )
        self.nodes[info.node_id] = info
        self.node_conns[info.node_id] = conn
        self._plane_node_up(info.node_id, conn)
        self._touch("nodes", info.node_id)
        if hasattr(self, "_restored_unregistered"):
            self._restored_unregistered.discard(info.node_id)
        if self.native_sched is not None:
            self.native_sched.update_node(
                info.node_id, total=info.total_resources,
                available=info.available_resources, labels=info.labels)
        conn.on_close(lambda: supervised_task(
            self._on_node_conn_lost(info.node_id, conn)))
        await self.publish("NODE", {"event": "alive", "node": info.to_wire()})
        logger.info("node %s registered (%s:%s)", info.node_id[:8], info.host, info.raylet_port)
        return {"ok": True, "config": self.config.to_json()}

    async def _handle_node_reregister(self, conn, node: NodeInfo, payload):
        """A live node re-registered over a fresh connection: a logged
        non-event. No migrations, no reconstructions — just re-bind the
        connection, clear SUSPECT, and preserve the drain ladder."""
        require_fields(payload, "host", "raylet_port",
                       method="RegisterNode")
        node.host = payload["host"]
        node.raylet_port = payload["raylet_port"]
        node.store_path = payload.get("store_path", node.store_path)
        node.transfer_port = payload.get("transfer_port", node.transfer_port)
        node.labels = payload.get("labels") or node.labels
        node.last_heartbeat = time.monotonic()
        was_suspect = node.state == NODE_SUSPECT
        if was_suspect:
            node.state = node.pre_suspect_state or NODE_ALIVE
            node.pre_suspect_state = ""
            outage_s = time.time() - node.suspect_since_s \
                if node.suspect_since_s else 0.0
            node.suspect_since_s = 0.0
            node.suspect_recoveries += 1
            logger.info(
                "node %s reconnected inside the grace window after %.1fs "
                "(flap #%d): non-event, state restored to %s",
                node.node_id[:8], outage_s, node.suspect_recoveries,
                node.state)
            from ray_tpu.util import events

            events.record("INFO", "gcs", "suspect node reconnected",
                          node_id=node.node_id)
        self.node_conns[node.node_id] = conn
        self._plane_node_up(node.node_id, conn)
        if node.state != NODE_ALIVE:
            # node_up resets the plane's rung to ALIVE; restore the real
            # one (e.g. a DRAINING node that flapped stays unpickable).
            self._plane_node_state_notify(node.node_id, node.state)
        self._touch("nodes", node.node_id)
        if self.native_sched is not None:
            self.native_sched.update_node(
                node.node_id, total=node.total_resources,
                available=node.available_resources, labels=node.labels,
                alive=node.state == NODE_ALIVE)
        conn.on_close(lambda: supervised_task(
            self._on_node_conn_lost(node.node_id, conn)))
        await self.publish("NODE", {
            "event": "reconnected" if was_suspect else "alive",
            "node": node.to_wire()})
        return {"ok": True, "config": self.config.to_json(),
                "reconnected": True}

    def _plane_node_up(self, node_id: str, conn) -> None:
        """Tell the native actor plane a raylet conn (re)bound, so it
        can (re)send any in-flight CreateActors over the fresh socket
        with their ORIGINAL (sid, rseq) — the raylet's reply cache
        makes the replay at-most-once."""
        if self._actor_plane is not None and hasattr(conn, "_conn_id"):
            try:
                self._actor_plane.node_up(node_id, conn._conn_id)
            except Exception:
                logger.exception("native actor plane node_up failed")

    def _plane_node_state_notify(self, node_id: str, state: str) -> None:
        """Mirror a death/drain-ladder rung into the native plane so
        native picks and re-drives honor SUSPECT/DRAINING exclusions."""
        if self._actor_plane is not None:
            try:
                self._actor_plane.node_state(node_id,
                                             _plane_node_state(state))
            except Exception:
                logger.exception("native actor plane node_state failed")

    async def _call_node(self, node_id: str, method: str, payload=None, *,
                         timeout: float | None = None,
                         wait_rebind: bool = True):
        """At-most-once GCS->raylet call.

        GCS->raylet RPCs ride the raylet-OPENED connection, so the GCS
        cannot redial a dead socket — it can only wait for the raylet to
        re-register (node_conns rebind). This helper stamps the request
        with a GCS-side per-node session id so a call replayed across
        that rebind hits the raylet's reply cache instead of executing a
        second time (a replayed CreateActor must not fork the actor).
        Waits up to the SUSPECT grace window for the rebind; raises
        rpc.ConnectionLost once the node is dead or the window expires.
        """
        sess = self._node_call_sessions.get(node_id)
        if sess is None:
            sess = self._node_call_sessions[node_id] = {
                "sid": uuid.uuid4().hex, "rseq": 0, "outstanding": set()}
        stamped = None
        rseq = 0
        if method not in rpc.SESSION_EXEMPT_METHODS \
                and (payload is None or isinstance(payload, dict)):
            sess["rseq"] += 1
            rseq = sess["rseq"]
            stamped = dict(payload or {})
            stamped[rpc._SID_KEY] = sess["sid"]
            stamped[rpc._RSEQ_KEY] = rseq
            sess["outstanding"].add(rseq)
        loop = asyncio.get_running_loop()
        grace = (self.config.health_check_period_s
                 * self.config.num_heartbeats_timeout)
        rebind_deadline = loop.time() + grace
        call_deadline = None if timeout is None else loop.time() + timeout
        sent_once = False
        try:
            while True:
                node = self.nodes.get(node_id)
                if node is None or not node.alive:
                    raise rpc.ConnectionLost(
                        f"node {node_id[:8]} is dead")
                conn = self.node_conns.get(node_id)
                if conn is None or conn.closed:
                    if not wait_rebind or loop.time() > rebind_deadline:
                        raise rpc.ConnectionLost(
                            f"no raylet connection to node {node_id[:8]}")
                    await asyncio.sleep(0.05)
                    continue
                if stamped is not None:
                    outstanding = sess["outstanding"]
                    stamped[rpc._ACK_KEY] = (min(outstanding) - 1
                                             if outstanding else sess["rseq"])
                if sent_once:
                    rpc._session_stats["replayed_requests_total"] += 1
                sent_once = True
                try:
                    att = None if call_deadline is None \
                        else max(0.01, call_deadline - loop.time())
                    return await conn.call(
                        method, stamped if stamped is not None else payload,
                        timeout=att)
                except rpc.ConnectionLost:
                    # Socket died mid-call: wait for the raylet to
                    # re-register, then replay (deduped server-side).
                    logger.debug(
                        "%s to node %s interrupted by connection loss; "
                        "awaiting re-registration to replay",
                        method, node_id[:8])
                    continue
        finally:
            if stamped is not None:
                sess["outstanding"].discard(rseq)

    async def handle_heartbeat(self, conn, payload):
        require_fields(payload, "node_id", method="handle_heartbeat")
        node = self.nodes.get(payload["node_id"])
        if node is None or not node.alive:
            # Explicit death notice: a raylet that outlived its own
            # SUSPECT->DEAD promotion (long partition healed) must not
            # be silently resurrected by a late heartbeat — its actors
            # and leases were already failed over. It must exit or
            # re-register as a fresh node.
            return {"ok": False, "dead": True,
                    "reason": "unknown or dead node; this identity was "
                              "declared dead — re-register as a new node"}
        if node.state == NODE_SUSPECT:
            # A heartbeat over a fresh connection from a SUSPECT node:
            # the node is clearly up, but its registration conn is gone.
            # Don't resurrect it from a side channel — tell it to re-run
            # the RegisterNode handshake (which rebinds node_conns and
            # clears SUSPECT as a non-event).
            return {"ok": False, "reregister": True,
                    "reason": "node is SUSPECT (connection lost); "
                              "re-register to reattach"}
        node.last_heartbeat = time.monotonic()
        node.available_resources = payload.get("available_resources", node.available_resources)
        if self.native_sched is not None:
            # A draining node keeps heartbeating but must stay dead in
            # the placement mirror (update_node defaults alive=True).
            self.native_sched.update_node(
                node.node_id, available=node.available_resources,
                alive=node.state == NODE_ALIVE)
        self.pending_demand[node.node_id] = payload.get("pending_demand", [])
        # Reply piggy-backs the cluster resource view so raylets can make
        # spillback decisions (replaces the reference's ray_syncer gossip,
        # reference: src/ray/common/ray_syncer/ray_syncer.h).
        return {"ok": True, "cluster": self._cluster_view()}

    def _cluster_view(self):
        return {
            nid: {
                "host": n.host,
                "raylet_port": n.raylet_port,
                "available_resources": n.available_resources,
                "total_resources": n.total_resources,
                "labels": n.labels,
                "transfer_port": n.transfer_port,
                # Same-host peers pull arena-to-arena through shm (one
                # memcpy, no sockets) — see raylet._native_pull.
                "store_path": n.store_path,
                # Raylets must not spill leases onto a DRAINING peer
                # (its object plane stays reachable for pulls).
                "state": n.state,
            }
            for nid, n in self.nodes.items()
            if n.alive
        }

    async def handle_get_all_nodes(self, conn, payload):
        return {"nodes": [n.to_wire() for n in self.nodes.values()]}

    async def handle_drain_node(self, conn, payload):
        """Start a graceful drain: DRAINING in the node table, Drain RPC
        to the raylet (reason + deadline), proactive actor migration.
        Failures PROPAGATE — a caller about to terminate the VM must
        know the node was never told to evacuate (the old handler
        swallowed every error and answered ok)."""
        require_fields(payload, "node_id", method="handle_drain_node")
        node_id = payload["node_id"]
        reason = payload.get("reason") or "manual"
        if reason not in DRAIN_REASONS:
            return {"ok": False, "error": f"unknown drain reason {reason!r} "
                                          f"(expected one of {DRAIN_REASONS})"}
        deadline_s = float(payload.get("deadline_s") or 30.0)
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "error": f"unknown node {node_id[:12]}"}
        if node.state == NODE_DRAINED:
            # Already evacuated (possibly self-drained on SIGTERM and
            # exited): idempotent success, even if the node is dead —
            # checked BEFORE aliveness or a clean self-drain would read
            # as a failed drain to the autoscaler/CLI.
            return {"ok": True, "state": NODE_DRAINED}
        if not node.alive:
            return {"ok": False, "error": f"node {node_id[:12]} is not alive"}
        nconn = self.node_conns.get(node_id)
        if (nconn is None or nconn.closed) and node.state != NODE_SUSPECT:
            # A SUSPECT node has no conn right now but may re-register
            # inside the grace window — _call_node below waits for the
            # rebind, so a drain issued during a flap still lands.
            return {"ok": False,
                    "error": f"no raylet connection to node {node_id[:12]}"}
        already_draining = node.state == NODE_DRAINING
        node.state = NODE_DRAINING
        # A drain overrides suspicion: clear the SUSPECT bookkeeping so a
        # later re-registration doesn't restore a stale pre-drain state.
        node.pre_suspect_state = ""
        node.suspect_since_s = 0.0
        node.drain_reason = reason
        node.drain_deadline_s = deadline_s
        node.drain_stats.setdefault("started_at", time.time())
        self._touch("nodes", node_id)
        self._plane_node_state_notify(node_id, NODE_DRAINING)
        # Placement mirror: stop picking the node for new actors/PGs
        # (the data plane keeps treating it as alive — objects are still
        # being pulled off it).
        if self.native_sched is not None:
            self.native_sched.update_node(node_id, available={}, alive=False)
        def rollback():
            # The raylet never accepted the drain: a node left DRAINING
            # here would be wedged out of placement forever (no
            # _run_drain is running, so DrainComplete never comes).
            if already_draining:
                return
            node.state = NODE_ALIVE
            node.drain_reason = ""
            self._touch("nodes", node_id)
            self._plane_node_state_notify(node_id, NODE_ALIVE)
            if self.native_sched is not None:
                self.native_sched.update_node(
                    node_id, available=node.available_resources,
                    alive=True)

        try:
            resp = await self._call_node(
                node_id, "Drain",
                {"reason": reason, "deadline_s": deadline_s},
                timeout=self.config.rpc_call_timeout_s)
        except Exception as e:
            rollback()
            return {"ok": False,
                    "error": f"drain rpc to raylet {node_id[:12]} failed: {e}"}
        if not resp.get("ok"):
            rollback()
            return {"ok": False,
                    "error": resp.get("error", "raylet refused drain")}
        from ray_tpu.util import events

        events.record("INFO", "gcs", f"node draining ({reason}, "
                      f"deadline {deadline_s:g}s)", node_id=node_id)
        await self.publish("NODE", {"event": "draining", "node_id": node_id,
                                    "reason": reason,
                                    "deadline_s": deadline_s})
        # Proactively restart restartable/named actors elsewhere while
        # the node is still up — callers observe a RESTARTING window,
        # never a dead-actor error. Once per drain: a repeated DrainNode
        # must not race a second migration pass into double-scheduling
        # the same actor (two CreateActors = a forked actor).
        if not already_draining:
            supervised_task(self._migrate_actors_off(node_id, reason))
        return {"ok": True, "state": NODE_DRAINING}

    async def _migrate_actors_off(self, node_id: str, reason: str):
        """Move every restartable (or detached/named) ALIVE actor off a
        draining node before it dies (reference: gcs_actor_manager's
        OnNodeDead reconstruction, run EARLY). Migration must not spend
        the user's failure budget: the incarnation number (restarts)
        bumps so callers reset their per-actor sequence counters, but
        max_restarts is extended to match."""
        node = self.nodes.get(node_id)
        migrated = 0
        for actor_id, a in list(self.actors.items()):
            if a.get("node_id") != node_id or a["state"] != ACTOR_ALIVE:
                continue
            restartable = (a["max_restarts"] == -1
                           or a["restarts"] < a["max_restarts"]
                           or a.get("detached") or a.get("name"))
            if not restartable:
                continue
            addr = a.get("address")
            if addr and len(addr) > 2:
                # Pre-record the current worker as dead so the eventual
                # death report from the raylet (kill below, or node
                # death) dedupes instead of consuming another restart.
                a.setdefault("dead_worker_ids", set()).add(addr[2])
            a["restarts"] += 1
            if a["max_restarts"] >= 0:
                a["max_restarts"] += 1  # migration is not a failure
            a["migrations"] = a.get("migrations", 0) + 1
            a["state"] = ACTOR_RESTARTING
            a["address"] = None
            self._touch("actors", actor_id)
            self.mark_dirty(("actors",))
            await self.publish("ACTOR", {
                "actor_id": actor_id, "state": ACTOR_RESTARTING,
                "reason": f"migrating off draining node ({reason})"})
            try:
                await self._call_node(
                    node_id, "KillActorWorker", {"actor_id": actor_id},
                    timeout=self.config.rpc_call_timeout_s)
            except Exception:
                pass  # node may die mid-drain; reschedule regardless
            migrated += 1
            supervised_task(self._schedule_actor(actor_id))
        if node is not None and migrated:
            node.drain_stats["migrated_actors"] = \
                node.drain_stats.get("migrated_actors", 0) + migrated
            self._touch("nodes", node_id)
            logger.info("migrated %d actor(s) off draining node %s",
                        migrated, node_id[:8])

    def _note_relocations(self, relocations: dict) -> None:
        for oid_hex, nid in relocations.items():
            if oid_hex not in self.object_relocations:
                self._relocation_order.append(oid_hex)
            self.object_relocations[oid_hex] = nid
        while len(self._relocation_order) > self._relocation_cap:
            self.object_relocations.pop(self._relocation_order.popleft(),
                                        None)

    async def handle_drain_complete(self, conn, payload):
        """The raylet finished evacuating: DRAINED in the node table,
        relocated-object directory updated, stats recorded. From here
        the node's death is expected and cheap."""
        require_fields(payload, "node_id", method="handle_drain_complete")
        node_id = payload["node_id"]
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "error": f"unknown node {node_id[:12]}"}
        self._note_relocations(payload.get("relocations") or {})
        node.state = NODE_DRAINED
        self._plane_node_state_notify(node_id, NODE_DRAINED)
        stats = dict(payload.get("stats") or {})
        # Merge: migrated_actors is GCS-side accounting, the rest is the
        # raylet's evacuation report.
        node.drain_stats.update(stats)
        self._touch("nodes", node_id)
        from ray_tpu.util import events

        events.record("INFO", "gcs", "node drained", node_id=node_id,
                      **{k: v for k, v in stats.items()
                         if isinstance(v, (int, float))})
        logger.info("node %s DRAINED (%s): %s", node_id[:8],
                    node.drain_reason or "?", node.drain_stats)
        await self.publish("NODE", {"event": "drained", "node_id": node_id,
                                    "stats": node.drain_stats})
        return {"ok": True, "state": NODE_DRAINED}

    async def handle_get_object_relocations(self, conn, payload):
        """Owner-side lookup: where did evacuated copies of these
        objects land? (Consulted before lineage reconstruction.)"""
        out = {}
        for oid_hex in payload.get("object_ids") or []:
            nid = self.object_relocations.get(oid_hex)
            if nid is not None:
                node = self.nodes.get(nid)
                if node is not None and node.alive:
                    out[oid_hex] = nid
        return {"relocations": out}

    async def handle_notify_node_dead(self, conn, payload):
        require_fields(payload, "node_id", method="handle_notify_node_dead")
        await self._mark_node_dead(payload["node_id"], payload.get("reason", "reported dead"))
        return {"ok": True}

    async def _on_node_conn_lost(self, node_id: str, conn=None):
        # Connection loss is a SUSPICION, not a death certificate: a
        # network flap or a GCS-side socket hiccup looks identical to a
        # crashed raylet at this layer. Mark the node SUSPECT (out of NEW
        # placement, nothing migrated) and let the heartbeat-timeout
        # expiry in _health_check_loop issue the actual death.
        if conn is not None and self.node_conns.get(node_id) is not conn:
            # A stale conn's close callback fired after the raylet
            # already re-registered over a fresh connection — suspecting
            # the healthy node now would be a false positive.
            return
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        if node.state == NODE_DRAINED:
            # An evacuated node hanging up is its expected exit — keep
            # the clean-removal path instead of a pointless grace window.
            await self._mark_node_dead(node_id, "raylet connection lost")
            return
        if node.state == NODE_SUSPECT:
            return
        node.pre_suspect_state = node.state
        node.state = NODE_SUSPECT
        node.suspect_since_s = time.time()
        # The plane parks (not forks) any in-flight create aimed at a
        # SUSPECT node: re-driven on reconnection, failed over on DEAD.
        self._plane_node_state_notify(node_id, NODE_SUSPECT)
        self.node_conns.pop(node_id, None)
        if self.native_sched is not None:
            self.native_sched.update_node(node_id, available={}, alive=False)
        self._touch("nodes", node_id)
        from ray_tpu.util import events

        grace = (self.config.health_check_period_s
                 * self.config.num_heartbeats_timeout)
        logger.info(
            "node %s connection lost: SUSPECT (grace %.1fs before "
            "promotion to DEAD)", node_id[:8], grace)
        events.record("INFO", "gcs", "node suspect: connection lost",
                      node_id=node_id)
        await self.publish("NODE", {"event": "suspect",
                                    "node": node.to_wire()})

    async def _mark_node_dead(self, node_id: str, reason: str):
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        drained = node.state == NODE_DRAINED
        node.alive = False
        node.state = NODE_DEAD if not drained else NODE_DRAINED
        node.available_resources = {}
        if self._actor_plane is not None:
            # The plane fails over its own in-flight creates (restart
            # bookkeeping + reschedule, narrated via inject events) —
            # BEFORE the loop below, whose skip of native PENDING actors
            # relies on the plane owning them.
            try:
                self._actor_plane.node_down(node_id)
            except Exception:
                logger.exception("native actor plane node_down failed")
        self.node_conns.pop(node_id, None)
        self._node_call_sessions.pop(node_id, None)
        if self.native_sched is not None:
            self.native_sched.update_node(node_id, available={}, alive=False)
        self.pending_demand.pop(node_id, None)
        self._touch("nodes", node_id)
        self.mark_dirty(("nodes", "actors", "placement_groups"))
        from ray_tpu.util import events

        if drained:
            # Expected death of an evacuated node: a non-event, not a
            # failure (no ERROR record, no unexpected-death log).
            logger.info("drained node %s removed cleanly (%s)",
                        node_id[:8], reason)
            events.record("INFO", "gcs", "drained node removed",
                          node_id=node_id)
        else:
            logger.warning("node %s dead: %s", node_id[:8], reason)
            events.record("ERROR", "gcs", f"node dead: {reason}",
                          node_id=node_id)
        await self.publish("NODE", {"event": "dead", "node_id": node_id,
                                    "reason": reason, "drained": drained})
        # Actor fault tolerance: restart or kill actors that lived there
        # (reference: gcs_actor_manager.cc OnNodeDead). On a DRAINED
        # node every restartable actor migrated before death; anything
        # left goes through the normal path with a drain-flavored cause.
        for actor_id, a in list(self.actors.items()):
            if a.get("node_id") == node_id and a["state"] in (ACTOR_ALIVE, ACTOR_PENDING):
                if a.get("native") and a["state"] == ACTOR_PENDING:
                    # In-flight native create: the node_down call above
                    # already failed it over inside the plane (restart
                    # consumed there); running the Python path too would
                    # double-count the restart.
                    continue
                await self._on_actor_worker_death(
                    actor_id,
                    f"node {node_id[:8]} drained and removed" if drained
                    else f"node {node_id[:8]} died: {reason}")
        for pg_id, pg in self.placement_groups.items():
            if pg["state"] == PG_CREATED and any(
                    b.get("node_id") == node_id for b in pg["bundles"]):
                supervised_task(self._schedule_pg(pg_id))

    async def _health_check_loop(self):
        # reference: gcs_health_check_manager.h:39 — gRPC health checks with
        # knobs from ray_config_def.h:813-819. Here: heartbeat staleness.
        period = self.config.health_check_period_s
        timeout = period * self.config.num_heartbeats_timeout
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                # Heads are exempt from heartbeat policing (the GCS lives
                # there) — EXCEPT once SUSPECT: a head whose connection
                # died and never came back must still be promoted.
                if node.alive and \
                        (not node.is_head or node.state == NODE_SUSPECT) \
                        and now - node.last_heartbeat > timeout:
                    reason = ("suspect grace expired (connection lost, "
                              "no re-registration)"
                              if node.state == NODE_SUSPECT
                              else "heartbeat timeout")
                    await self._mark_node_dead(node.node_id, reason)

    # ---------- KV ----------

    async def handle_kv_put(self, conn, payload):
        require_fields(payload, "key", "value", method="handle_kv_put")
        ns = payload.get("ns", "")
        table = self.kv[ns]
        key = payload["key"]
        if not payload.get("overwrite", True) and key in table:
            return {"added": False}
        table[key] = payload["value"]
        self._touch("kv", (ns, key))
        return {"added": True}

    async def handle_kv_get(self, conn, payload):
        require_fields(payload, "key", method="handle_kv_get")
        return {"value": self.kv[payload.get("ns", "")].get(payload["key"])}

    async def handle_kv_del(self, conn, payload):
        require_fields(payload, "key", method="handle_kv_del")
        existed = self.kv[payload.get("ns", "")].pop(payload["key"], None) is not None
        if existed:
            self._touch("kv", (payload.get("ns", ""), payload["key"]))
        return {"deleted": existed}

    async def handle_kv_keys(self, conn, payload):
        prefix = payload.get("prefix", b"")
        return {"keys": [k for k in self.kv[payload.get("ns", "")] if k.startswith(prefix)]}

    async def handle_kv_exists(self, conn, payload):
        require_fields(payload, "key", method="handle_kv_exists")
        return {"exists": payload["key"] in self.kv[payload.get("ns", "")]}

    # ---------- actors ----------

    async def handle_register_actor(self, conn, payload):
        """Register + schedule an actor (reference: gcs_actor_manager.cc
        RegisterActor → GcsActorScheduler)."""
        require_fields(payload, "actor_id", "spec",
                       method="handle_register_actor")
        actor_id = payload["actor_id"]
        spec = payload["spec"]
        name = payload.get("name") or ""
        namespace = payload.get("namespace") or "default"
        if name:
            key = (namespace, name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing["state"] != ACTOR_DEAD:
                    if payload.get("get_if_exists"):
                        return {"ok": True, "existing": True, "actor_id": self.named_actors[key]}
                    return {"ok": False,
                            "reason": f"actor name {name!r} already taken in {namespace!r}"}
            self.named_actors[key] = actor_id
            self._touch("named_actors", key)
        self.actors[actor_id] = {
            "actor_id": actor_id,
            "job_id": payload.get("job_id", ""),
            "name": name,
            "namespace": namespace,
            "class_name": payload.get("class_name", ""),
            "state": ACTOR_PENDING,
            "spec": spec,
            "resources": normalize_resources(payload.get("resources")),
            "max_restarts": payload.get("max_restarts", 0),
            "restarts": 0,
            "node_id": None,
            "address": None,
            "detached": payload.get("detached", False),
            "owner": payload.get("owner"),
            "death_cause": None,
            "strategy": payload.get("strategy"),
            "placement_group": payload.get("placement_group", ""),
            "pg_bundle_index": payload.get("pg_bundle_index", -1),
        }
        self._touch("actors", actor_id)
        self._record_task_event(
            self._creation_task_id(actor_id, spec), payload.get("class_name", ""),
            "CREATE_REGISTERED", job_id=payload.get("job_id", ""),
            actor_id=actor_id)
        supervised_task(self._schedule_actor(actor_id))
        return {"ok": True}

    def _pick_node_for(self, resources: dict, strategy=None,
                       pg_id: str = "", bundle_index: int = -1) -> str | None:
        """Node selection for actors/PGs at the GCS (raylets do their own
        hybrid policy for tasks). Mirrors the reference's GcsActorScheduler
        falling back onto raylet scheduling."""
        # DRAINING/DRAINED nodes take no new placements (their native-
        # scheduler mirror is already marked dead at drain start).
        alive = [n for n in self.nodes.values()
                 if n.alive and n.state == NODE_ALIVE]
        if strategy and strategy[0] == "node_affinity":
            target, soft = strategy[1], strategy[2]
            node = self.nodes.get(target)
            if node is not None and node.alive and node.state == NODE_ALIVE:
                return target
            if not soft:
                return None
        if pg_id:
            pg = self.placement_groups.get(pg_id)
            if not pg or pg["state"] != PG_CREATED:
                return None
            bundles = pg["bundles"]
            if bundle_index >= 0:
                return bundles[bundle_index].get("node_id")
            for b in bundles:
                node = self.nodes.get(b.get("node_id") or "")
                if node and node.alive and resources_fit(b["available"], resources):
                    return b["node_id"]
            return None
        if self.native_sched is not None:
            strat = "spread" if (strategy and strategy[0] == "spread") else "pack"
            return self.native_sched.pick_node(resources, strat,
                                               fallback_total=True)
        candidates = [n for n in alive if resources_fit(n.available_resources, resources)]
        if not candidates:
            # Fall back to nodes that could EVER fit (total resources) —
            # the raylet will queue the lease until resources free up.
            candidates = [n for n in alive if resources_fit(n.total_resources, resources)]
        if not candidates:
            return None
        if strategy and strategy[0] == "spread":
            candidates.sort(key=lambda n: sum(
                n.total_resources.get(k, 0) - n.available_resources.get(k, 0)
                for k in ("CPU", "TPU", "GPU")))
            return candidates[0].node_id
        # Default: pack onto the most-utilized node that fits (hybrid-ish).
        candidates.sort(key=lambda n: -sum(
            n.total_resources.get(k, 0) - n.available_resources.get(k, 0)
            for k in ("CPU", "TPU", "GPU")))
        return candidates[0].node_id

    async def _schedule_actor(self, actor_id: str, delay: float = 0.0):
        if delay:
            await asyncio.sleep(delay)
        a = self.actors.get(actor_id)
        if a is None or a["state"] == ACTOR_DEAD:
            return
        # Resource-less actors hold nothing while alive, but placement still
        # charges 1 CPU so creations spread and land on feasible nodes
        # (reference: actor creation schedules against num_cpus=1, runs
        # against num_cpus=0).
        placement_demand = a["resources"]
        if not placement_demand and not a.get("placement_group"):
            placement_demand = {"CPU": 1.0}
        node_id = self._pick_node_for(
            placement_demand, a.get("strategy"), a.get("placement_group", ""),
            a.get("pg_bundle_index", -1))
        if node_id is None or node_id not in self.node_conns:
            # No feasible node right now; retry (autoscaler demand signal).
            supervised_task(self._schedule_actor(actor_id, delay=0.5))
            return
        # Transient debit of the placement demand against the GCS view: a
        # burst of concurrent creations fans out across nodes instead of
        # herding onto one stale "best" node. The next heartbeat from the
        # raylet restores ground truth (real holds are debited there).
        node = self.nodes.get(node_id)
        if node is not None:
            subtract_resources(node.available_resources, placement_demand)
        if self.native_sched is not None:
            self.native_sched.debit_node(node_id, placement_demand)
        a["node_id"] = node_id
        self.mark_dirty(("actors",))
        self._record_task_event(
            self._creation_task_id(actor_id, a["spec"]), a["class_name"],
            "CREATE_SCHEDULED", job_id=a.get("job_id", ""),
            actor_id=actor_id, target_node=node_id)
        try:
            # _call_node, not a raw conn.call: a socket flap mid-create
            # replays the request after the raylet re-registers, and the
            # raylet's reply cache guarantees the actor is created at
            # most once (a forked actor is the worst control-plane bug).
            resp = await self._call_node(
                node_id, "CreateActor",
                {"actor_id": actor_id, "spec": a["spec"], "resources": a["resources"],
                 "placement_group": a.get("placement_group", ""),
                 "pg_bundle_index": a.get("pg_bundle_index", -1)},
                timeout=self.config.rpc_call_timeout_s)
            if not resp.get("ok"):
                reason = resp.get("reason", "creation failed")
                if "draining" in reason:
                    # Creation raced a drain: not a failure, just pick a
                    # different node — consuming a restart here would
                    # spend the user's budget on an infrastructure event.
                    logger.info("actor %s creation bounced off draining "
                                "node %s; rescheduling", actor_id[:8],
                                node_id[:8])
                    supervised_task(
                        self._schedule_actor(actor_id, delay=0.2))
                    return
                logger.warning("actor %s creation on node %s failed: %s",
                               actor_id[:8], node_id[:8], reason)
                await self._on_actor_worker_death(actor_id, reason)
        except Exception as e:
            logger.warning("actor %s creation rpc to node %s failed: %s",
                           actor_id[:8], node_id[:8], e)
            await self._on_actor_worker_death(actor_id, f"creation rpc failed: {e}")

    async def handle_actor_ready(self, conn, payload):
        require_fields(payload, "actor_id", "address",
                       method="handle_actor_ready")
        a = self.actors.get(payload["actor_id"])
        if a is None:
            return {"ok": False}
        a["state"] = ACTOR_ALIVE
        a["address"] = payload["address"]
        self._touch("actors", payload["actor_id"])
        self._record_task_event(
            self._creation_task_id(payload["actor_id"], a["spec"]),
            a["class_name"], "CREATE_READY", job_id=a.get("job_id", ""),
            actor_id=payload["actor_id"])
        # restarts doubles as the incarnation number: callers reset their
        # per-actor sequence numbers when it changes (reference: the client
        # queue resend path in direct_actor_task_submitter).
        await self.publish("ACTOR", {"actor_id": a["actor_id"], "state": ACTOR_ALIVE,
                                     "address": a["address"],
                                     "restarts": a["restarts"]})
        return {"ok": True}

    async def handle_report_actor_death(self, conn, payload):
        # Dedupe: a single worker death can surface through several signals
        # (process reap, socket close); only the first report per worker
        # may consume a restart (reference: ReconstructActor checks the
        # dead worker matches the actor's current incarnation).
        require_fields(payload, "actor_id", method="handle_report_actor_death")
        a = self.actors.get(payload["actor_id"])
        wid = payload.get("worker_id")
        if a is not None and wid:
            seen = a.setdefault("dead_worker_ids", set())
            if wid in seen:
                return {"ok": True}
            seen.add(wid)
        await self._on_actor_worker_death(payload["actor_id"],
                                          payload.get("reason", "worker died"),
                                          intended=payload.get("intended", False))
        return {"ok": True}

    async def _on_actor_worker_death(self, actor_id: str, reason: str, intended: bool = False):
        """reference: gcs_actor_manager.h:504 ReconstructActor — restart with
        backoff while restarts remain, else mark DEAD and notify callers."""
        a = self.actors.get(actor_id)
        if a is None or a["state"] == ACTOR_DEAD:
            return
        if a.pop("native", None) and self._actor_plane is not None:
            # Python takes over this actor's lifecycle (post-create
            # death, kill, node failure of an ALIVE actor): the plane
            # must drop its record or a later node event would make it
            # act on a ghost.
            try:
                self._actor_plane.actor_forget(actor_id)
            except Exception:
                logger.exception("native actor plane forget failed")
        can_restart = (not intended) and (
            a["max_restarts"] == -1 or a["restarts"] < a["max_restarts"])
        logger.info("actor %s worker died (%s), restart=%s (%d/%s)",
                    actor_id[:8], reason, can_restart, a["restarts"],
                    a["max_restarts"])
        if can_restart:
            a["restarts"] += 1
            a["state"] = ACTOR_RESTARTING
            a["address"] = None
            self._touch("actors", actor_id)
            self.mark_dirty(("actors",))
            await self.publish("ACTOR", {"actor_id": actor_id, "state": ACTOR_RESTARTING,
                                         "reason": reason})
            supervised_task(self._schedule_actor(actor_id))
        else:
            a["state"] = ACTOR_DEAD
            self.mark_dirty(("actors", "named_actors"))
            a["address"] = None
            a["death_cause"] = reason
            self.named_actors.pop((a["namespace"], a["name"]), None)
            self._touch("actors", actor_id)
            self._touch("named_actors", (a["namespace"], a["name"]))
            from ray_tpu.util import events

            events.record("WARNING", "gcs", "actor dead",
                          actor_id=actor_id)
            await self.publish("ACTOR", {"actor_id": actor_id, "state": ACTOR_DEAD,
                                         "reason": reason})

    async def handle_get_actor_info(self, conn, payload):
        require_fields(payload, "actor_id", method="handle_get_actor_info")
        if self.recovering and payload["actor_id"] not in self.actors:
            aid_hex = payload["actor_id"].encode().hex()
            self._recovery_faultin(
                lambda it: it[0] == "actors" and it[1] == aid_hex)
        a = self.actors.get(payload["actor_id"])
        if a is None:
            return {"found": False}
        return {"found": True, "state": a["state"], "address": a["address"],
                "death_cause": a["death_cause"], "restarts": a["restarts"],
                "class_name": a["class_name"], "name": a["name"]}

    async def handle_get_named_actor(self, conn, payload):
        require_fields(payload, "name", method="handle_get_named_actor")
        key = (payload.get("namespace") or "default", payload["name"])
        if self.recovering and key not in self.named_actors:
            # The name index and its target row may both still be on
            # the stream: fault in the index, then the actor it names.
            self._recovery_faultin(lambda it: it[0] == "named_actors")
            target = self.named_actors.get(key)
            if target is not None and target not in self.actors:
                t_hex = target.encode().hex()
                self._recovery_faultin(
                    lambda it: it[0] == "actors" and it[1] == t_hex)
        actor_id = self.named_actors.get(key)
        if actor_id is None or actor_id not in self.actors:
            return {"found": False}
        a = self.actors[actor_id]
        return {"found": True, "actor_id": actor_id, "state": a["state"],
                "address": a["address"], "spec_meta": a["spec"].get("meta")
                if isinstance(a["spec"], dict) else None}

    async def handle_list_actors(self, conn, payload):
        if self.recovering:
            self._recovery_faultin(lambda it: it[0] == "actors")
        return {"actors": [
            {k: a[k] for k in ("actor_id", "job_id", "name", "namespace", "class_name",
                               "state", "node_id", "restarts", "resources")}
            for a in self.actors.values()]}

    async def handle_kill_actor(self, conn, payload):
        require_fields(payload, "actor_id", method="handle_kill_actor")
        actor_id = payload["actor_id"]
        if self.recovering and actor_id not in self.actors:
            aid_hex = actor_id.encode().hex()
            self._recovery_faultin(
                lambda it: it[0] == "actors" and it[1] == aid_hex)
        a = self.actors.get(actor_id)
        if a is None:
            return {"ok": False}
        no_restart = payload.get("no_restart", True)
        if no_restart:
            a["max_restarts"] = a["restarts"]  # exhaust restarts
        node_id = a.get("node_id")
        if node_id in self.node_conns:
            try:
                await self._call_node(
                    node_id, "KillActorWorker", {"actor_id": actor_id},
                    timeout=self.config.rpc_call_timeout_s)
            except Exception:
                # Best-effort: the raylet may already be tearing the
                # worker down; the death path below is authoritative.
                logger.warning("kill_actor(%s): KillActorWorker rpc to "
                               "node %s failed", actor_id[:8], node_id[:8],
                               exc_info=True)
        if a["state"] != ACTOR_DEAD and no_restart:
            await self._on_actor_worker_death(actor_id, "killed via kill()", intended=True)
        return {"ok": True}

    # ---------- jobs ----------

    async def handle_register_job(self, conn, payload):
        require_fields(payload, "job_id", method="handle_register_job")
        if payload.get("owns_cluster"):
            # This driver started the session (local mode): the whole tree
            # dies with it — GCS exits, raylets exit on GCS loss, workers
            # exit on raylet loss.  Prevents orphaned daemons when the
            # driver is killed (reference: ray.init() local session
            # lifetime is the driver's lifetime).
            loop = asyncio.get_running_loop()

            def _driver_gone():
                import os

                logger.warning("owning driver for job %s disconnected; "
                               "shutting down session", payload["job_id"][:8])
                loop.call_later(0.2, lambda: os._exit(0))

            conn.on_close(_driver_gone)
        self.jobs[payload["job_id"]] = {
            "job_id": payload["job_id"],
            "driver_address": payload.get("driver_address"),
            "start_time": time.time(),
            "end_time": None,
            "status": "RUNNING",
            "entrypoint": payload.get("entrypoint", ""),
        }
        self._touch("jobs", payload["job_id"])
        return {"ok": True}

    async def handle_finish_job(self, conn, payload):
        require_fields(payload, "job_id", method="handle_finish_job")
        if self.recovering and payload["job_id"] not in self.jobs:
            jid_hex = payload["job_id"].encode().hex()
            self._recovery_faultin(
                lambda it: it[0] == "jobs" and it[1] == jid_hex)
        job = self.jobs.get(payload["job_id"])
        if job:
            job["status"] = payload.get("status", "SUCCEEDED")
            job["end_time"] = time.time()
            self._touch("jobs", payload["job_id"])
        # Raylets release the job's runtime-env references on this event
        # (reference: runtime-env URI GC when the last referencing job
        # exits, runtime_env ARCHITECTURE.md).
        await self.publish("JOB", {"event": "finished",
                                   "job_id": payload["job_id"]})
        return {"ok": True}

    async def handle_list_jobs(self, conn, payload):
        if self.recovering:
            self._recovery_faultin(lambda it: it[0] == "jobs")
        return {"jobs": list(self.jobs.values())}

    # ---------- placement groups ----------

    async def handle_create_pg(self, conn, payload):
        require_fields(payload, "bundles", "pg_id", method="handle_create_pg")
        pg_id = payload["pg_id"]
        bundles = [{"resources": normalize_resources(b), "node_id": None, "available": {}}
                   for b in payload["bundles"]]
        self.placement_groups[pg_id] = {
            "pg_id": pg_id,
            "name": payload.get("name", ""),
            "strategy": payload.get("strategy", "PACK"),
            "bundles": bundles,
            "state": PG_PENDING,
            "job_id": payload.get("job_id", ""),
        }
        self._touch("placement_groups", pg_id)
        supervised_task(self._schedule_pg(pg_id))
        return {"ok": True}

    async def _schedule_pg(self, pg_id: str, delay: float = 0.0):
        """2-phase bundle reservation (reference:
        gcs_placement_group_scheduler.cc Prepare/Commit) with PACK / SPREAD /
        STRICT_PACK / STRICT_SPREAD and the TPU-first STRICT_ICI strategy:
        all bundles must land on nodes of one ICI-connected slice (same
        `tpu-slice` label), the gang-lease unit for multi-host TPU pods."""
        if delay:
            await asyncio.sleep(delay)
        pg = self.placement_groups.get(pg_id)
        if pg is None or pg["state"] != PG_PENDING:
            return
        placement = self._pack_bundles(pg)
        if placement is None:
            supervised_task(self._schedule_pg(pg_id, delay=0.5))
            return
        # Prepare on all nodes.
        prepared = []
        ok = True
        for idx, node_id in placement:
            if node_id not in self.node_conns:
                ok = False
                break
            try:
                resp = await self._call_node(node_id, "PreparePGBundle", {
                    "pg_id": pg_id, "bundle_index": idx,
                    "resources": pg["bundles"][idx]["resources"]})
                if not resp.get("ok"):
                    ok = False
                    break
                prepared.append((idx, node_id))
            except Exception:
                ok = False
                break
        if not ok:
            for idx, node_id in prepared:
                try:
                    await self._call_node(
                        node_id, "ReturnPGBundle",
                        {"pg_id": pg_id, "bundle_index": idx})
                except Exception:
                    pass
            supervised_task(self._schedule_pg(pg_id, delay=0.5))
            return
        for idx, node_id in placement:
            try:
                await self._call_node(
                    node_id, "CommitPGBundle",
                    {"pg_id": pg_id, "bundle_index": idx})
            except Exception:
                pass
            pg["bundles"][idx]["node_id"] = node_id
            pg["bundles"][idx]["available"] = dict(pg["bundles"][idx]["resources"])
        pg["state"] = PG_CREATED
        self.mark_dirty(("placement_groups",))
        await self.publish("PG", {"pg_id": pg_id, "state": PG_CREATED,
                                  "bundles": [(b["node_id"]) for b in pg["bundles"]]})

    def _pack_bundles(self, pg) -> list[tuple[int, str]] | None:
        """Returns [(bundle_index, node_id)] or None if infeasible now."""
        strategy = pg["strategy"]
        if self.native_sched is not None:
            got = self.native_sched.schedule_bundles(
                [b["resources"] for b in pg["bundles"]], strategy)
            if got is None:
                return None
            return list(enumerate(got))
        alive = [n for n in self.nodes.values()
                 if n.alive and n.state == NODE_ALIVE]
        if strategy == "STRICT_ICI":
            # Group nodes by slice label; try each slice as a unit.
            slices: dict[str, list[NodeInfo]] = defaultdict(list)
            for n in alive:
                label = n.labels.get("tpu-slice")
                if label:
                    slices[label].append(n)
            for nodes in slices.values():
                placement = self._fit_bundles(pg["bundles"], nodes, spread=False)
                if placement is not None:
                    return placement
            return None
        if strategy in ("SPREAD", "STRICT_SPREAD"):
            placement = self._fit_bundles(pg["bundles"], alive, spread=True,
                                          strict=strategy == "STRICT_SPREAD")
            return placement
        if strategy == "STRICT_PACK":
            for n in sorted(alive, key=lambda n: -sum(n.available_resources.values())):
                placement = self._fit_bundles(pg["bundles"], [n], spread=False)
                if placement is not None:
                    return placement
            return None
        return self._fit_bundles(pg["bundles"], alive, spread=False)

    def _fit_bundles(self, bundles, nodes, spread: bool, strict: bool = False):
        avail = {n.node_id: dict(n.available_resources) for n in nodes}
        order = list(nodes)
        placement = []
        used_nodes = set()
        for idx, b in enumerate(bundles):
            res = b["resources"]
            placed = False
            if spread:
                order.sort(key=lambda n: len([1 for i, nid in placement if nid == n.node_id]))
            for n in order:
                if strict and n.node_id in used_nodes:
                    continue
                if resources_fit(avail[n.node_id], res):
                    subtract_resources(avail[n.node_id], res)
                    placement.append((idx, n.node_id))
                    used_nodes.add(n.node_id)
                    placed = True
                    break
            if not placed:
                return None
        return placement

    def _faultin_pg(self, pg_id: str) -> None:
        if self.recovering and pg_id not in self.placement_groups:
            pid_hex = pg_id.encode().hex()
            self._recovery_faultin(
                lambda it: it[0] == "placement_groups" and it[1] == pid_hex)

    async def handle_remove_pg(self, conn, payload):
        require_fields(payload, "pg_id", method="handle_remove_pg")
        self._faultin_pg(payload["pg_id"])
        pg = self.placement_groups.get(payload["pg_id"])
        if pg is None:
            return {"ok": False}
        for idx, b in enumerate(pg["bundles"]):
            node_id = b.get("node_id")
            if node_id and node_id in self.node_conns:
                try:
                    await self._call_node(
                        node_id, "ReturnPGBundle",
                        {"pg_id": pg["pg_id"], "bundle_index": idx})
                except Exception:
                    # A dead raylet frees its bundles via node-death
                    # cleanup; log so a live one failing is visible.
                    logger.warning("remove_pg(%s): ReturnPGBundle %d on "
                                   "node %s failed", pg["pg_id"][:8], idx,
                                   node_id[:8], exc_info=True)
        pg["state"] = PG_REMOVED
        self._touch("placement_groups", payload["pg_id"])
        # Waiters on ready() promises fail instead of hanging forever.
        await self.publish("PG", {"pg_id": payload["pg_id"],
                                  "state": PG_REMOVED})
        return {"ok": True}

    async def handle_get_pg(self, conn, payload):
        require_fields(payload, "pg_id", method="handle_get_pg")
        self._faultin_pg(payload["pg_id"])
        pg = self.placement_groups.get(payload["pg_id"])
        if pg is None:
            return {"found": False}
        return {"found": True, "state": pg["state"],
                "bundles": [{"node_id": b["node_id"], "resources": b["resources"]}
                            for b in pg["bundles"]],
                "strategy": pg["strategy"], "name": pg["name"]}

    async def handle_list_pgs(self, conn, payload):
        if self.recovering:
            self._recovery_faultin(lambda it: it[0] == "placement_groups")
        return {"placement_groups": [
            {"pg_id": pg["pg_id"], "name": pg["name"], "state": pg["state"],
             "strategy": pg["strategy"],
             "bundles": [{"node_id": b["node_id"], "resources": b["resources"]}
                         for b in pg["bundles"]]}
            for pg in self.placement_groups.values()]}

    # ---------- task events / status ----------

    def _record_task_event(self, task_id: str, name: str, state: str,
                           **extra) -> None:
        """GCS-side lifecycle stamp (actor CREATE stages): lands in the
        same task-event table worker stamps flush into, keyed by the
        creation task id so the per-actor ladder merges with the
        executing worker's ARGS_FETCHED/RUNNING/FINISHED stamps."""
        ev = {"task_id": task_id, "name": name, "state": state,
              "node_id": "gcs", "worker_id": "gcs",
              "job_id": extra.pop("job_id", ""), "ts": time.time()}
        if extra:
            ev.update(extra)
        self.task_events.append(ev)

    @staticmethod
    def _creation_task_id(actor_id: str, spec_wire) -> str:
        # TaskSpec.to_wire is a list with task_id first; fall back to the
        # actor id for exotic/legacy spec payloads.
        if isinstance(spec_wire, (list, tuple)) and spec_wire \
                and isinstance(spec_wire[0], str):
            return spec_wire[0]
        return actor_id

    async def handle_add_task_events(self, conn, payload):
        require_fields(payload, "events", method="handle_add_task_events")
        self.task_events.extend(payload["events"])
        return {"ok": True}

    async def handle_list_task_events(self, conn, payload):
        limit = payload.get("limit", 1000)
        events = list(self.task_events)[-limit:]
        return {"events": events}

    async def handle_get_cluster_status(self, conn, payload):
        return {
            "nodes": [n.to_wire() for n in self.nodes.values()],
            "pending_demand": [d for demands in self.pending_demand.values()
                               for d in demands],
            "pending_placement_groups": [
                {"strategy": pg["strategy"],
                 "bundles": [b["resources"] for b in pg["bundles"]]}
                for pg in self.placement_groups.values()
                if pg["state"] == PG_PENDING],
            "actors": len([a for a in self.actors.values() if a["state"] == ACTOR_ALIVE]),
            "placement_groups": len([p for p in self.placement_groups.values()
                                     if p["state"] == PG_CREATED]),
            "uptime_s": time.time() - self.start_time,
            "suspect_nodes": len([n for n in self.nodes.values()
                                  if n.state == NODE_SUSPECT]),
            "rpc_sessions": rpc.session_stats(),
            "native_control": self._native_control_stats(),
            "fanout": dict(self._fanout_stats),
            "recovering": self.recovering,
            "recovery": dict(self._recovery_stats,
                             backlog_rows=len(self._recovery_backlog)),
        }

    def _native_control_stats(self):
        if self._actor_plane is None:
            return None
        plane = self._actor_plane
        handled, fallthrough, deduped = plane.counters()
        methods = {}
        for m in ("RegisterActor", "ActorReady"):
            mh, mr, md = plane.method_stats(m)
            methods[m] = {"handled": mh, "routed": mr, "degraded": md}
        return {
            "handled_total": handled,
            # Frames the plane looked at but routed to Python (complex
            # shapes, transient no-node states, unknown actors).
            "native_fallthrough_total": fallthrough,
            "deduped_requests_total": deduped,
            "actors": plane.actor_count(),
            "sessions": plane.session_count(),
            "proto_errors": plane.proto_errors(),
            # Replayed pre-restart frames rejected by the epoch handshake
            # (clients re-issue; never wrongly deduped against the lost
            # reply cache).
            "stale_epoch_rejections_total": plane.stale_epoch_total(),
            # Frames the divergence breaker pushed back to Python.
            "native_degraded_total": plane.degraded_total(),
            "divergence_trips_total": self._native_divergence_trips,
            "degraded_reason": self._native_degraded_reason,
            "methods": methods,
        }

    # ---------- native mirror audit (divergence breaker) ----------

    async def _native_audit_loop(self):
        """Periodically compare the Python mirror with the native
        plane's tables. Two consecutive mismatched sweeps (in-flight
        ladders make single-sweep skew normal) or a proto-error burst
        trips the breaker: the plane's owned methods degrade to the
        Python handlers (counted native_degraded_total) and stay there —
        re-arming needs an operator restart, because a real divergence
        must be understood, not retried."""
        period = max(1.0, self.config.health_check_period_s)
        prev_mismatch = ""
        while True:
            await asyncio.sleep(period)
            plane = self._actor_plane
            if plane is None or self._native_degraded_reason:
                return
            try:
                proto = plane.proto_errors()
                burst = proto - self._audit_proto_seen >= 10
                self._audit_proto_seen = proto
                mismatch = self._native_mirror_mismatch(plane)
                if burst:
                    self._trip_native_breaker(
                        f"proto-error burst ({proto} total)")
                elif mismatch and prev_mismatch:
                    self._trip_native_breaker(mismatch)
                prev_mismatch = mismatch
            except Exception:
                logger.exception("native mirror audit sweep failed")

    def _native_mirror_mismatch(self, plane) -> str:
        """One audit sweep; returns a divergence description or ''."""
        py_native = {aid: a for aid, a in self.actors.items()
                     if a.get("native") and a["state"] != ACTOR_DEAD}
        n_plane = plane.actor_count()
        if n_plane != len(py_native):
            return (f"actor-count divergence: plane={n_plane} "
                    f"mirror={len(py_native)}")
        for aid, a in py_native.items():
            pstate = plane.actor_state(aid)
            if pstate is None:
                return f"actor {aid[:8]} missing from native plane"
            # ALIVE in the mirror comes only from the plane's own ready
            # event, so the plane must agree; PENDING/RESTARTING can
            # legitimately lag one event behind.
            if a["state"] == ACTOR_ALIVE and pstate != "ALIVE":
                return (f"actor {aid[:8]} state divergence: "
                        f"plane={pstate} mirror=ALIVE")
        return ""

    def _trip_native_breaker(self, reason: str) -> None:
        plane = self._actor_plane
        if plane is None or self._native_degraded_reason:
            return
        self._native_degraded_reason = reason
        self._native_divergence_trips += 1
        for m in ("RegisterActor", "ActorReady"):
            try:
                plane.set_degraded(m, True)
            except Exception:
                logger.exception("native breaker trip failed for %s", m)
        logger.error("native control plane DEGRADED to Python: %s",
                     reason)
        from ray_tpu.util import events

        events.record("ERROR", "gcs",
                      f"native control plane degraded: {reason}")

    async def handle_get_event_loop_stats(self, conn, payload):
        """Event-loop/RPC dispatch stats for the GCS pump (analogue of
        the reference's event_stats.h surface): per-handler call counts
        and latencies from the server's EventLoopStats, plus the native
        in-pump service's counters (frames it handled never reach the
        Python dispatch table, so they are reported separately)."""
        out = {"server": self._server.stats.snapshot()}
        if self._native_svc is not None:
            handled, appends, fails = self._native_svc.counters()
            n_ns, n_rows = self._native_svc.kv_stats()
            out["native"] = {
                "handled": handled, "wal_appends": appends,
                "wal_failures": fails,
                "proto_errors": self._native_svc.proto_errors(),
                "kv_namespaces": n_ns, "kv_rows": n_rows,
            }
        else:
            out["native"] = None
        out["native_control"] = self._native_control_stats()
        return out

    async def handle_get_config(self, conn, payload):
        return {"config": self.config.to_json()}


def main():
    """Entrypoint: `python -m ray_tpu._private.gcs --port=... `"""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--config", default="")
    parser.add_argument("--persist", default="")
    parser.add_argument("--ready-fd", type=int, default=-1)
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="[gcs] %(asctime)s %(levelname)s %(message)s")
    import faulthandler

    faulthandler.enable()  # segfault/abort tracebacks land in gcs.log
    _maybe_attach_daemon_profiler("gcs")

    async def run():
        # Eager tasks (3.12): an RPC dispatch that completes without
        # blocking never round-trips through the scheduler — one fewer
        # loop hop per table mutation on the daemon hot path. Absent on
        # older interpreters; the daemon must still boot there.
        if hasattr(asyncio, "eager_task_factory"):
            asyncio.get_running_loop().set_task_factory(
                asyncio.eager_task_factory)
        config = Config.from_json(args.config) if args.config else Config()
        server = GcsServer(config, persistence_path=args.persist or None)
        host, port = await server.start(args.host, args.port)
        if args.ready_fd >= 0:
            import os
            os.write(args.ready_fd, f"{host}:{port}\n".encode())
            os.close(args.ready_fd)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
