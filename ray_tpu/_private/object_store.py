"""Python client for the native shared-memory object store.

The store itself is C++ (src/object_store.cc, built to
ray_tpu/_private/_lib/libtpustore.so); this module loads it via ctypes and
adds the zero-copy read path: `get_buffer` returns a memoryview directly
into the shared mapping so numpy / jax.device_put consume object payloads
without a copy (reference parity: plasma client mmap reads,
src/ray/object_manager/plasma/client.cc).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.native_build import ensure_built

# Error codes matching src/object_store.cc
OK = 0
ERR_NOT_FOUND = -1
ERR_EXISTS = -2
ERR_OUT_OF_MEMORY = -3
ERR_NOT_SEALED = -4
ERR_TABLE_FULL = -5
ERR_IN_USE = -6


class ObjectStoreError(Exception):
    pass


class ObjectStoreFullError(ObjectStoreError):
    pass


_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built("object_store.cc", "libtpustore.so",
                                       extra_flags=("-lpthread",)))
        lib.store_create_arena.restype = ctypes.c_void_p
        lib.store_create_arena.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.store_attach.restype = ctypes.c_void_p
        lib.store_attach.argtypes = [ctypes.c_char_p]
        lib.store_detach.argtypes = [ctypes.c_void_p]
        lib.store_create.restype = ctypes.c_int
        lib.store_create.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        for name in ("store_seal", "store_release", "store_abort"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_get.restype = ctypes.c_int
        lib.store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.store_contains.restype = ctypes.c_int
        lib.store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_delete.restype = ctypes.c_int
        lib.store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.store_list.restype = ctypes.c_int
        lib.store_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.store_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.store_set_auto_evict.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.store_set_auto_evict.restype = None
        lib.store_lru_candidates.restype = ctypes.c_int
        lib.store_lru_candidates.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_int]
        _lib = lib
    return _lib


class ObjectStoreClient:
    """Per-process handle on the node's shared-memory arena."""

    def __init__(self, path: str, create: bool = False, size: int = 0, table_capacity: int = 65536):
        lib = _get_lib()
        self._lib = lib
        self._path = path
        # RLock: release() can re-enter on the SAME thread when a ctypes
        # call triggers cyclic GC that collects a _ShmPin (whose __del__
        # calls release again) — a plain Lock would self-deadlock.
        self._release_lock = threading.RLock()
        if create:
            self._handle = lib.store_create_arena(path.encode(), size, table_capacity)
        else:
            self._handle = lib.store_attach(path.encode())
        if not self._handle:
            raise ObjectStoreError(f"failed to open object store arena at {path}")
        # Own mmap for zero-copy python-side reads/writes.
        fd = os.open(path, os.O_RDWR)
        try:
            self._map_size = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, self._map_size)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)

    @property
    def path(self) -> str:
        return self._path

    def create(self, object_id: ObjectID, data_size: int, meta_size: int = 0) -> memoryview:
        """Allocate an object; returns writable view. Caller must seal()."""
        off = ctypes.c_uint64()
        rc = self._lib.store_create(self._handle, object_id.binary(), data_size, meta_size,
                                    ctypes.byref(off))
        if rc == ERR_EXISTS:
            raise ObjectStoreError(f"object {object_id.hex()} already exists")
        if rc in (ERR_OUT_OF_MEMORY, ERR_TABLE_FULL):
            raise ObjectStoreFullError(
                f"object store full creating {data_size} bytes (rc={rc})")
        if rc != OK:
            raise ObjectStoreError(f"create failed rc={rc}")
        return self._view[off.value: off.value + data_size]

    def seal(self, object_id: ObjectID) -> None:
        rc = self._lib.store_seal(self._handle, object_id.binary())
        if rc != OK:
            raise ObjectStoreError(f"seal failed rc={rc}")

    def put_raw(self, object_id: ObjectID, data: bytes, meta: bytes = b"") -> None:
        buf = self.create(object_id, len(meta) + len(data), len(meta))
        if meta:
            buf[: len(meta)] = meta
        buf[len(meta):] = data
        self.seal(object_id)

    def get_buffer(self, object_id: ObjectID):
        """Returns (meta: bytes, data: memoryview) zero-copy, or None if absent.

        Increments the shm refcount; call release() when the consumer is done
        (dropping references to the memoryview is not enough).
        """
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        meta_size = ctypes.c_uint64()
        rc = self._lib.store_get(self._handle, object_id.binary(), ctypes.byref(off),
                                 ctypes.byref(size), ctypes.byref(meta_size))
        if rc in (ERR_NOT_FOUND, ERR_NOT_SEALED):
            return None
        if rc != OK:
            raise ObjectStoreError(f"get failed rc={rc}")
        start = off.value
        meta = bytes(self._view[start: start + meta_size.value])
        data = self._view[start + meta_size.value: start + size.value]
        return meta, data

    def release(self, object_id: ObjectID) -> None:
        # May be called from GC (_ShmPin.__del__) on any thread, possibly
        # after close() at shutdown: the lock + None check keep a late
        # release from reaching C with a detached handle (segfault).
        with self._release_lock:
            if self._handle is None:
                return
            self._lib.store_release(self._handle, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        return bool(self._lib.store_contains(self._handle, object_id.binary()))

    def delete(self, object_id: ObjectID, force: bool = False) -> bool:
        """force=True frees even while readers hold references — only the
        owner-driven distributed-refcount GC path may use it (a forced free
        under a live zero-copy view recycles memory mid-read)."""
        return self._lib.store_delete(self._handle, object_id.binary(), 1 if force else 0) == OK

    def abort(self, object_id: ObjectID) -> None:
        self._lib.store_abort(self._handle, object_id.binary())

    def list_objects(self, max_n: int = 65536) -> list[ObjectID]:
        buf = ctypes.create_string_buffer(max_n * ObjectID.SIZE)
        n = self._lib.store_list(self._handle, buf, max_n)
        raw = buf.raw
        return [ObjectID(raw[i * 20:(i + 1) * 20]) for i in range(n)]

    def set_auto_evict(self, enabled: bool) -> None:
        """Off = create() reports OOM instead of evicting, so the raylet
        can spill idle objects to disk first (spilled copies are
        restorable; evicted ones are gone until lineage re-executes)."""
        self._lib.store_set_auto_evict(self._handle, 1 if enabled else 0)

    def lru_candidates(self, needed: int, max_n: int = 4096) -> list[ObjectID]:
        """LRU-first sealed refcount==0 objects totalling >= needed bytes."""
        buf = ctypes.create_string_buffer(max_n * ObjectID.SIZE)
        n = self._lib.store_lru_candidates(self._handle, needed, buf, max_n)
        raw = buf.raw
        return [ObjectID(raw[i * 20:(i + 1) * 20]) for i in range(n)]

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 5)()
        self._lib.store_stats(self._handle, out)
        return {
            "num_objects": out[0],
            "bytes_in_use": out[1],
            "heap_size": out[2],
            "num_evictions": out[3],
            "num_creates": out[4],
        }

    def close(self) -> None:
        with self._release_lock:
            handle, self._handle = self._handle, None
        if handle:
            try:
                self._view.release()
                self._mm.close()
            except BufferError:
                # Zero-copy views handed to callers are still alive; leave
                # the mapping open (the OS reclaims it at process exit).
                pass
            self._lib.store_detach(handle)
