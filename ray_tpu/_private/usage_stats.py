"""Opt-out usage stats (parity: reference python/ray/_private/usage/ —
usage_lib.py collects cluster metadata on a schedule and reports it).

This build runs in egress-free environments, so the "report" sink is a
JSON file in the session directory instead of an HTTPS endpoint; the
collection schema (cluster metadata, library usage tags, counters) and
the RAY_TPU_USAGE_STATS_ENABLED=0 opt-out match the reference's shape.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_library_usages: set[str] = set()
_extra_tags: dict[str, str] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False")


def record_library_usage(library: str) -> None:
    """Called on first use of data/train/tune/serve/rllib (reference:
    usage_lib.record_library_usage)."""
    with _lock:
        _library_usages.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    with _lock:
        _extra_tags[key] = str(value)


def _collect(gcs_call=None) -> dict:
    import ray_tpu

    data = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "python_version": sys.version.split()[0],
        "os": sys.platform,
        "collected_at": time.time(),
        "libraries": sorted(_library_usages),
        "extra_tags": dict(_extra_tags),
    }
    # Passive only: NEVER import jax or initialize a backend from the
    # reporter. `jax.default_backend()` here used to spin up a PJRT
    # client inside every driver — a multi-second import racing user
    # work, a second tunnel client per driver on TPU machines, and PJRT
    # teardown aborts at exit. Record what's already in the process;
    # accelerator inventory comes from the cluster resource view below.
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        data["jax_version"] = getattr(jax_mod, "__version__", "unknown")
    try:
        nodes = ray_tpu.nodes()
        data["num_nodes"] = sum(1 for n in nodes if n.get("alive"))
        data["total_resources"] = ray_tpu.cluster_resources()
    except Exception:
        pass
    return data


class UsageStatsReporter:
    """Periodic collector writing usage_stats.json into the session dir."""

    def __init__(self, session_dir: str, interval_s: float = 300.0):
        self.path = os.path.join(session_dir, "usage_stats.json")
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if not usage_stats_enabled():
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="usage-stats")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            self.report_once()
            if self._stop.wait(self.interval_s):
                return

    def report_once(self) -> None:
        try:
            with open(self.path + ".tmp", "w") as f:
                json.dump(_collect(), f, indent=2, default=str)
            os.replace(self.path + ".tmp", self.path)
        except Exception:
            pass

    def stop(self) -> None:
        self._stop.set()
        # Join, don't just signal: a daemon thread still unwinding when
        # the interpreter finalizes gets pthread_exit'd mid-GIL-acquire,
        # which glibc turns into 'FATAL: exception not rethrown' + abort
        # (seen ~1-in-5 under load). Aim for dead-before-stop-returns;
        # if a report is wedged mid-RPC past the timeout, KEEP the
        # handle so a second stop() can re-join instead of losing track
        # of a live thread.
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
            if t.is_alive():
                logger.warning(
                    "usage-stats reporter still alive after stop(): a "
                    "report is blocked; interpreter exit may race it")
                return
        self._thread = None
