"""ctypes binding for the native cluster-resource scheduler.

The scheduler itself is C++ (src/scheduler.cc, built to
ray_tpu/_private/_lib/libtpusched.so) — the TPU-native equivalent of the
reference's C++ scheduling stack (reference:
src/ray/raylet/scheduling/cluster_resource_scheduler.h:44,
policy/hybrid_scheduling_policy.h, policy/bundle_scheduling_policy.h).
The GCS (actor/PG placement) and raylet (spillback) call into it; if the
toolchain is unavailable the callers keep their pure-Python paths.
"""

from __future__ import annotations

import ctypes

from ray_tpu._private.native_build import ensure_built

_lib = None
_lib_failed = False


def _get_lib():
    global _lib, _lib_failed
    if _lib is None and not _lib_failed:
        try:
            lib = ctypes.CDLL(ensure_built("scheduler.cc", "libtpusched.so"))
        except Exception:
            _lib_failed = True
            return None
        lib.sched_create.restype = ctypes.c_void_p
        lib.sched_destroy.argtypes = [ctypes.c_void_p]
        lib.sched_update_node.restype = ctypes.c_int
        lib.sched_update_node.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.sched_remove_node.restype = ctypes.c_int
        lib.sched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.sched_num_nodes.restype = ctypes.c_int
        lib.sched_num_nodes.argtypes = [ctypes.c_void_p]
        lib.sched_debit_node.restype = ctypes.c_int
        lib.sched_debit_node.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
        lib.sched_pick_node.restype = ctypes.c_int
        lib.sched_pick_node.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint,
            ctypes.c_char_p, ctypes.c_int]
        lib.sched_schedule_bundles.restype = ctypes.c_int
        lib.sched_schedule_bundles.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        _lib = lib
    return _lib


def available() -> bool:
    return _get_lib() is not None


# Entries separated by ASCII RS (0x1e), key split on the FIRST '=' — values
# with commas or '=' survive the round-trip (resource/label names must not
# contain RS or '=', which normalize_resources never produces).
_SEP = "\x1e"


def _enc_resources(res: dict | None) -> bytes:
    return _SEP.join(f"{k}={float(v):.10g}"
                     for k, v in (res or {}).items()).encode()


def _enc_labels(labels: dict | None) -> bytes:
    return _SEP.join(f"{k}={v}" for k, v in (labels or {}).items()).encode()


class ClusterScheduler:
    """Cluster node table + placement policies, backed by the C++ core.

    Thread-safe (the C++ side holds its own mutex); callers feed it node
    state (register/heartbeat/death) and ask for placements.
    """

    FALLBACK_TOTAL = 1  # pick_node flag: fall back to total-capacity fit

    def __init__(self):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.sched_create())
        self._seed = 0

    def close(self):
        if self._h:
            self._lib.sched_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def update_node(self, node_id: str, total: dict | None = None,
                    available: dict | None = None, labels: dict | None = None,
                    alive: bool = True):
        self._lib.sched_update_node(
            self._h, node_id.encode(),
            None if total is None else _enc_resources(total),
            None if available is None else _enc_resources(available),
            None if labels is None else _enc_labels(labels),
            1 if alive else 0)

    def remove_node(self, node_id: str):
        self._lib.sched_remove_node(self._h, node_id.encode())

    def debit_node(self, node_id: str, demand: dict):
        self._lib.sched_debit_node(self._h, node_id.encode(),
                                   _enc_resources(demand))

    def num_nodes(self) -> int:
        return self._lib.sched_num_nodes(self._h)

    def pick_node(self, demand: dict, strategy: str = "hybrid", *,
                  exclude: str = "", fallback_total: bool = False,
                  seed: int | None = None) -> str | None:
        """strategy: 'hybrid' | 'pack' | 'spread' | 'affinity:<id>:<0|1>'."""
        out = ctypes.create_string_buffer(256)
        if seed is None:
            self._seed = (self._seed + 1) & 0xFFFFFFFF
            seed = self._seed
        rc = self._lib.sched_pick_node(
            self._h, _enc_resources(demand), strategy.encode(),
            exclude.encode(), self.FALLBACK_TOTAL if fallback_total else 0,
            seed, out, len(out))
        return out.value.decode() if rc == 0 else None

    def schedule_bundles(self, bundles: list[dict], strategy: str = "PACK",
                         ici_label_key: str = "tpu-slice"
                         ) -> list[str] | None:
        """Gang placement. Returns node ids in bundle order, or None."""
        enc = b"|".join(_enc_resources(b) for b in bundles)
        out = ctypes.create_string_buffer(64 + 256 * max(1, len(bundles)))
        rc = self._lib.sched_schedule_bundles(
            self._h, enc, strategy.encode(), ici_label_key.encode(),
            out, len(out))
        if rc != 0:
            return None
        return out.value.decode().split(",")
