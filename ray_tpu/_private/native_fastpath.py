"""ctypes binding for the native fastpath frame pump (src/fastpath.cc).

The RPC hot path in C++: one epoll thread per pump owns accept/connect,
msgpack framing, read buffering, and writev-coalesced sends, so the
steady-state task cycle (PushTaskBatch → execute → TaskDone) never
touches Python asyncio (reference analog: the gRPC/asio event loops of
core_worker.cc and node_manager.cc — the daemons' hot loops are native
end-to-end).

Two consumption styles over the same FIFO:
  - `next(timeout)` — blocking dequeue (GIL released inside ctypes);
    worker exec threads live here.
  - `eventfd` — plain eventfd counter bumped per queued event (when
    armed); a driver asyncio loop `add_reader()`s it, read()s it to
    zero at callback entry, then drains until empty — a push racing the
    drain re-bumps it, so the level-triggered reader re-fires.
"""

from __future__ import annotations

import ctypes
import os
import threading

from ray_tpu._private.native_build import ensure_built

# Event kinds (src/fastpath.cc EventKind).
EV_FRAME = 1
EV_ACCEPT = 2
EV_CLOSE = 3
EV_INJECT = 4

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = ensure_built("fastpath.cc", "libtpufastpath.so",
                            extra_flags=("-lpthread",))
        lib = ctypes.CDLL(path)
        lib.fpump_create.restype = ctypes.c_void_p
        lib.fpump_destroy.argtypes = [ctypes.c_void_p]
        lib.fpump_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.fpump_listen.restype = ctypes.c_int
        lib.fpump_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int]
        lib.fpump_connect.restype = ctypes.c_int64
        lib.fpump_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fpump_send.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.c_char_p, ctypes.c_uint32]
        lib.fpump_send.restype = ctypes.c_int
        lib.fpump_inject.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_char_p, ctypes.c_uint32]
        lib.fpump_recv_eventfd.argtypes = [ctypes.c_void_p]
        lib.fpump_recv_eventfd.restype = ctypes.c_int
        lib.fpump_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int]
        lib.fpump_next.restype = ctypes.c_int
        lib.fpump_arm_eventfd.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.fpump_set_service.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_void_p, ctypes.c_void_p]
        lib.fpump_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.fpump_drain.restype = ctypes.c_int
        _lib = lib
        return lib


def available() -> bool:
    if os.environ.get("RAY_TPU_FASTPATH", "1") in ("0", "false", "no"):
        return False
    try:
        _load()
        return True
    except Exception:
        return False


class FastPump:
    """One native frame pump (epoll thread + event FIFO)."""

    def __init__(self):
        self._lib = _load()
        self._h = self._lib.fpump_create()
        if not self._h:
            raise OSError("fpump_create failed")
        # Reusable receive buffer per consumer thread (events are copied
        # out of C; 256 KiB covers every control frame — data frames of a
        # push batch can exceed it and trigger a one-shot regrow).
        self._buf_tls = threading.local()
        self._closed = False

    # ---- endpoints ----

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        got = self._lib.fpump_listen(self._h, host.encode(), port)
        if got < 0:
            raise OSError(f"fpump_listen failed (host={host} port={port})")
        return got

    def connect(self, host: str, port: int) -> int:
        cid = self._lib.fpump_connect(self._h, host.encode(), port)
        if cid < 0:
            raise OSError(f"fastpath connect to {host}:{port} failed")
        return cid

    def close_conn(self, conn_id: int) -> None:
        if not self._closed:
            self._lib.fpump_close_conn(self._h, conn_id)

    # ---- IO ----

    def set_service(self, frame_fn_addr: int, close_fn_addr: int,
                    ctx: int) -> None:
        """Install an in-pump native service (loop-thread frame handler).
        Must be called before listen()/connect() — the loop thread reads
        the hook fields without a lock."""
        self._lib.fpump_set_service(self._h, frame_fn_addr, close_fn_addr,
                                    ctx)

    def send(self, conn_id: int, payload: bytes) -> bool:
        """Queue one frame body; returns False if the conn is gone."""
        if self._closed:
            return False
        return self._lib.fpump_send(self._h, conn_id, payload,
                                    len(payload)) == 0

    def inject(self, token: int, payload: bytes = b"") -> None:
        """Queue a local work item into the event FIFO (kind=EV_INJECT)."""
        if not self._closed:
            self._lib.fpump_inject(self._h, token, payload, len(payload))

    @property
    def eventfd(self) -> int:
        return self._lib.fpump_recv_eventfd(self._h)

    def next(self, timeout: float | None):
        """Dequeue the next event: (kind, conn_id, payload_bytes) or None
        on timeout. Blocking (GIL released) when timeout > 0 / None."""
        if self._closed:
            return None
        tls = self._buf_tls
        buf = getattr(tls, "buf", None)
        if buf is None:
            buf = tls.buf = ctypes.create_string_buffer(1 << 18)
        conn_id = ctypes.c_int64()
        kind = ctypes.c_int()
        n = ctypes.c_uint32(len(buf))
        tmo = -1 if timeout is None else int(timeout * 1000)
        r = self._lib.fpump_next(self._h, ctypes.byref(conn_id),
                                 ctypes.byref(kind), buf, ctypes.byref(n),
                                 tmo)
        if r == -2:  # payload larger than the buffer: regrow and retry
            buf = tls.buf = ctypes.create_string_buffer(int(n.value))
            n = ctypes.c_uint32(len(buf))
            r = self._lib.fpump_next(self._h, ctypes.byref(conn_id),
                                     ctypes.byref(kind), buf,
                                     ctypes.byref(n), tmo)
        if r != 1:
            return None
        return kind.value, conn_id.value, buf.raw[:n.value]

    def arm_eventfd(self, armed: bool = True) -> None:
        """Enable recv-eventfd bumps (driver asyncio consumers only)."""
        if not self._closed:
            self._lib.fpump_arm_eventfd(self._h, 1 if armed else 0)

    def drain(self, max_events: int = 512):
        """Non-blocking batch dequeue: one ctypes call returns up to
        max_events events as a list of (kind, conn_id, payload_bytes)."""
        if self._closed:
            return []
        tls = self._buf_tls
        buf = getattr(tls, "dbuf", None)
        if buf is None:
            buf = tls.dbuf = ctypes.create_string_buffer(1 << 20)
        needed = ctypes.c_uint32(0)
        out = []
        while True:
            needed.value = 0
            n = self._lib.fpump_drain(self._h, buf, len(buf), max_events,
                                      ctypes.byref(needed))
            if n == 0:
                if needed.value > len(buf):  # single oversized event
                    buf = tls.dbuf = ctypes.create_string_buffer(
                        int(needed.value))
                    continue
                # Queue genuinely empty — the ONLY exit without a
                # follow-up call: a short batch may mean buffer-full or
                # the per-call cap, and stopping there would strand
                # events behind an already-zeroed eventfd.
                return out
            raw = ctypes.string_at(buf, int(needed.value))  # used bytes only
            off = 0
            for _ in range(n):
                conn_id = int.from_bytes(raw[off:off + 8], "little",
                                         signed=True)
                kind = int.from_bytes(raw[off + 8:off + 12], "little")
                dlen = int.from_bytes(raw[off + 12:off + 16], "little")
                out.append((kind, conn_id, raw[off + 16:off + 16 + dlen]))
                off += 16 + dlen
            if len(out) >= max_events:
                return out

    # ---- lifecycle ----

    def close(self) -> None:
        """Destroy the pump. Caller contract: every thread that may be
        blocked in next() must have been stopped/joined first (the C side
        wakes them on stop, but destroy then frees the handle)."""
        if self._closed:
            return
        self._closed = True
        self._lib.fpump_destroy(self._h)
        self._h = None
