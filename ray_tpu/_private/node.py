"""Cluster process bring-up: spawn GCS + raylet daemons, connect drivers.

Parity: reference python/ray/_private/node.py:40 (Node),
node.py:1395 (start_head_processes), services.py:1314 (start_gcs_server),
services.py:1378 (start_raylet).
"""

from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
import uuid

from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID


def _spawn_with_ready(cmd: list[str], log_path: str, timeout: float = 30.0):
    """Spawn a daemon with a ready-fd pipe; returns (proc, ready_line)."""
    read_fd, write_fd = os.pipe()
    os.set_inheritable(write_fd, True)
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    log_file = open(log_path, "ab")
    proc = subprocess.Popen(
        cmd + [f"--ready-fd={write_fd}"],
        pass_fds=(write_fd,),
        stdout=log_file, stderr=subprocess.STDOUT,
        start_new_session=True)
    log_file.close()
    os.close(write_fd)
    deadline = time.monotonic() + timeout
    buf = b""
    with os.fdopen(read_fd, "rb") as r:
        while time.monotonic() < deadline:
            chunk = r.readline()
            if chunk:
                buf = chunk
                break
            if proc.poll() is not None:
                break
            time.sleep(0.01)
    if not buf:
        proc.kill()
        raise RuntimeError(
            f"daemon failed to start: {' '.join(cmd)}; see {log_path}")
    return proc, buf.decode().strip()


class NodeHandle:
    """A raylet process started by this driver/test (one per simulated node)."""

    def __init__(self, proc, node_id: str, host: str, port: int, store_path: str):
        self.proc = proc
        self.node_id = node_id
        self.host = host
        self.port = port
        self.store_path = store_path

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except Exception:
            pass
        # A SIGKILLed raylet never unlinks its arena; do it here so dead
        # clusters don't pin /dev/shm memory.
        import os

        try:
            os.unlink(self.store_path)
        except OSError:
            pass

    def preempt(self):
        """Deliver a platform preemption notice (SIGTERM) to the raylet.
        Its preemption watcher (raylet.main) self-initiates a graceful
        drain with the RAY_TPU_PREEMPTION_DEADLINE_S deadline (30s
        default) and exits 0 once DRAINED — the spot/maintenance
        reclaim path, exercised by test_utils.NodePreempter."""
        import signal as _signal

        try:
            self.proc.send_signal(_signal.SIGTERM)
        except Exception:
            pass


class RuntimeNode:
    """Drives head bring-up and node management for one session."""

    def __init__(self, config: Config | None = None):
        self.config = config or Config()
        session_id = uuid.uuid4().hex[:8]
        self.session_dir = os.path.join(self.config.temp_dir, f"session-{session_id}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.gcs_proc = None
        self.gcs_host: str | None = None
        self.gcs_port: int | None = None
        self.nodes: list[NodeHandle] = []
        self._atexit_registered = False

    def start_gcs(self, port: int = 0):
        self.gcs_persist_path = os.path.join(self.session_dir,
                                             "gcs_state.msgpack")
        proc, line = _spawn_with_ready(
            [sys.executable, "-m", "ray_tpu._private.gcs",
             f"--config={self.config.to_json()}",
             f"--port={port}",
             f"--persist={self.gcs_persist_path}"],
            os.path.join(self.session_dir, "logs", "gcs.log"))
        self.gcs_proc = proc
        host, port_s = line.rsplit(":", 1)
        self.gcs_host, self.gcs_port = host, int(port_s)
        self._register_atexit()
        return host, int(port_s)

    def kill_gcs(self):
        """SIGKILL the GCS (fault-injection; reference: GCS FT tests)."""
        if self.gcs_proc is not None:
            try:
                self.gcs_proc.kill()
                self.gcs_proc.wait(timeout=5)
            except Exception:
                pass
            self.gcs_proc = None

    def restart_gcs(self):
        """Restart the GCS on the SAME port with its persisted state
        (reference: GCS restarts with Redis persistence; raylets resync
        via NotifyGCSRestart, node_manager.cc:1168)."""
        assert self.gcs_port, "GCS never started"
        return self.start_gcs(port=self.gcs_port)

    def attach_gcs(self, host: str, port: int):
        self.gcs_host, self.gcs_port = host, port

    def start_raylet(self, resources: dict | None = None, labels: dict | None = None,
                     is_head: bool = False,
                     gcs_addr: tuple[str, int] | None = None) -> NodeHandle:
        """gcs_addr overrides the GCS endpoint this raylet dials — the
        hook chaos tests use to route one node's control-plane traffic
        through a NetChaos proxy (test_utils.NetChaos)."""
        assert self.gcs_host is not None, "start or attach GCS first"
        gcs_host, gcs_port = gcs_addr or (self.gcs_host, self.gcs_port)
        node_id = NodeID.from_random().hex()
        cmd = [sys.executable, "-m", "ray_tpu._private.raylet",
               f"--gcs-host={gcs_host}", f"--gcs-port={gcs_port}",
               f"--session-dir={self.session_dir}",
               f"--resources={json.dumps(resources or {})}",
               f"--labels={json.dumps(labels or {})}",
               f"--node-id={node_id}"]
        if is_head:
            cmd.append("--head")
        proc, line = _spawn_with_ready(
            cmd, os.path.join(self.session_dir, "logs", f"raylet-{node_id[:8]}.log"))
        host, port, nid, store_path = line.split(":", 3)
        handle = NodeHandle(proc, nid, host, int(port), store_path)
        self.nodes.append(handle)
        return handle

    def _register_atexit(self):
        if not self._atexit_registered:
            atexit.register(self.shutdown)
            self._atexit_registered = True

    def shutdown(self):
        for n in self.nodes:
            n.kill()
        self.nodes.clear()
        if self.gcs_proc is not None:
            try:
                self.gcs_proc.kill()
                self.gcs_proc.wait(timeout=5)
            except Exception:
                pass
            self.gcs_proc = None
