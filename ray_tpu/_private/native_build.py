"""Shared build-on-first-use helper for the native C++ components
(src/*.cc → ray_tpu/_private/_lib/*.so, loaded via ctypes)."""

from __future__ import annotations

import os
import subprocess
import threading

LIB_DIR = os.path.join(os.path.dirname(__file__), "_lib")
SRC_DIR = os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "..", "src"))

_build_lock = threading.Lock()


def ensure_built(src_name: "str | tuple[str, ...]", lib_name: str,
                 extra_flags: tuple[str, ...] = (),
                 dep_names: tuple[str, ...] = ()) -> str:
    """Compile src/<src_name(s)> to _lib/<lib_name> if stale; returns the
    lib path. `dep_names` are non-compiled dependencies (headers) that
    participate in the staleness check only. Compiles to a private temp
    file then os.replace()s: concurrent processes (GCS + raylet on a
    fresh checkout) must never dlopen a half-written .so."""
    names = (src_name,) if isinstance(src_name, str) else tuple(src_name)
    srcs = [os.path.join(SRC_DIR, n) for n in names]
    deps = srcs + [os.path.join(SRC_DIR, n) for n in dep_names]
    lib_path = os.path.join(LIB_DIR, lib_name)
    with _build_lock:
        existing = [s for s in deps if os.path.exists(s)]
        if os.path.exists(lib_path) and (
            not existing
            or os.path.getmtime(lib_path) >= max(os.path.getmtime(s)
                                                 for s in existing)
        ):
            return lib_path
        os.makedirs(LIB_DIR, exist_ok=True)
        tmp = f"{lib_path}.{os.getpid()}.tmp"
        subprocess.run(
            [os.environ.get("CXX", "g++"),
             "-O2", "-Wall", "-fPIC", "-std=c++17", "-shared",
             "-o", tmp, *srcs, *extra_flags],
            check=True, capture_output=True)
        os.replace(tmp, lib_path)
    return lib_path
