"""Shared runtime structures: task specs, resources, addresses.

Parity: reference src/ray/common/task/task_spec.h (TaskSpecification),
src/ray/common/scheduling/resource_set.h (fixed-point resource math — here
plain floats with an epsilon), and the owner address embedded in object refs
(reference: src/ray/protobuf/common.proto Address).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any

logger = logging.getLogger(__name__)

RESOURCE_EPS = 1e-9


# ---------- supervised task spawning (graftlint rule R1) ----------
# asyncio.create_task/ensure_future keep only a WEAK reference to the
# spawned task (it can be GC'd mid-flight) and park escaped exceptions
# on the task object where nobody reads them. Both shapes have produced
# real outages here: the lease pump died on an escaped
# ConnectionRefusedError and wedged the task queue for 120s (PR 2), and
# conn-retirement leaked pending recv tasks as GC cycles (r4 teardown
# flake). supervised_task() is the ONLY sanctioned way to fire-and-
# forget a coroutine — graftlint R1 flags every raw spawn.

_BG_TASKS: set = set()
_task_stats = {"spawned": 0, "errors_total": 0, "ignored_total": 0}


def supervised_task(coro, *, name: str = "", tasks: set | None = None,
                    ignore: tuple = (), on_error=None, log=None):
    """Spawn `coro` as an asyncio task that cannot die silently.

    - Holds a strong reference until the task finishes (in the
      module-level registry, or in `tasks` if the caller needs its own
      cancellation set, e.g. FastRpcServer._inflight).
    - Attaches a done-callback that logs any escaped exception and bumps
      the `errors_total` counter (see task_stats()).
    - `ignore`: exception types that are an expected end-state for this
      task (e.g. ConnectionLost on a best-effort notify); they are
      counted and logged at DEBUG instead of ERROR.
    - `on_error(exc)`: optional hook run before logging.

    Returns the task, so callers may still await/cancel it.
    """
    import asyncio

    task = asyncio.ensure_future(coro)  # graftlint: disable=R1
    if name:
        try:
            task.set_name(name)
        except AttributeError:
            pass
    registry = _BG_TASKS if tasks is None else tasks
    registry.add(task)
    _task_stats["spawned"] += 1
    lg = log or logger

    def _done(t, registry=registry):
        registry.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is None:
            return
        if ignore and isinstance(exc, ignore):
            _task_stats["ignored_total"] += 1
            lg.debug("supervised task %r finished with expected %r",
                     name or str(t), exc)
            return
        _task_stats["errors_total"] += 1
        if on_error is not None:
            try:
                on_error(exc)
            except Exception:
                lg.exception("supervised task %r: on_error hook failed",
                             name or str(t))
        lg.error("supervised task %r died with escaped exception",
                 name or str(t), exc_info=exc)

    task.add_done_callback(_done)
    return task


def task_stats() -> dict:
    """Snapshot of supervised-task counters (spawned/errors/ignored)."""
    return dict(_task_stats)


# ---------- retry policy (session layer; graftlint rule R6) ----------
# Every transient-failure loop in the tree used to roll its own
# delay/except tuple; two of them busy-looped with no jitter and one
# swallowed EMFILE as a bring-up race. RetryPolicy is the ONE shape:
# jittered exponential backoff, a total deadline, and a transient/
# permanent classifier that refuses to retry resource-exhaustion and
# permission errnos.

import errno as _errno

# Local resource exhaustion / misconfiguration: retrying cannot help and
# only hides the bug (the EMFILE class of failure).
_NON_TRANSIENT_ERRNOS = frozenset({
    _errno.EMFILE, _errno.ENFILE, _errno.EACCES, _errno.EPERM,
    _errno.EBADF, _errno.EAFNOSUPPORT, _errno.EPROTONOSUPPORT,
})


@dataclass
class RetryPolicy:
    """Jittered exponential backoff with a total deadline.

    `run(fn)` awaits `fn()` until it succeeds, the deadline expires, or
    a non-transient exception escapes. Transient means: connection-level
    failures (refused/reset/pipe), timeouts, and OSErrors whose errno is
    NOT in the non-transient set; anything in `also_transient` joins the
    set (e.g. rpc.ConnectionLost, which common can't import).
    """

    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5           # each delay drawn from [d*(1-j), d]
    deadline_s: float = 10.0      # total budget; float("inf") = forever
    also_transient: tuple = ()

    def is_transient(self, exc: BaseException) -> bool:
        import asyncio

        if self.also_transient and isinstance(exc, self.also_transient):
            return True
        if isinstance(exc, (ConnectionRefusedError, ConnectionResetError,
                            BrokenPipeError, ConnectionAbortedError,
                            asyncio.TimeoutError, TimeoutError)):
            return True
        if isinstance(exc, OSError):
            return exc.errno not in _NON_TRANSIENT_ERRNOS
        return False

    def delay(self, attempt: int) -> float:
        """Backoff for retry number `attempt` (0-based), jittered."""
        import random

        d = min(self.max_delay_s,
                self.base_delay_s * (self.multiplier ** attempt))
        return d * (1.0 - self.jitter * random.random())

    async def run(self, fn, *, name: str = "", log=None):
        """Await `fn()` under this policy. On deadline expiry the LAST
        transient exception is re-raised (not a generic TimeoutError) so
        callers keep their existing except clauses."""
        import asyncio

        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.deadline_s
        attempt = 0
        while True:
            try:
                return await fn()
            except BaseException as e:
                if not self.is_transient(e):
                    raise
                d = self.delay(attempt)
                if loop.time() + d > deadline:
                    raise
                (log or logger).debug(
                    "%s: transient %r; retry %d in %.2fs",
                    name or getattr(fn, "__name__", "retry"), e, attempt, d)
                attempt += 1
                await asyncio.sleep(d)


# ---------- request-frame validation (graftlint rule R5) ----------

class MalformedError(Exception):
    """A request frame failed field validation.

    Raised by require_fields(); the RPC dispatchers turn it into a
    MSG_ERROR response whose text carries "Malformed" — same contract
    as the native service's Malformed() replies (src/gcs_service.cc) —
    instead of a KeyError traceback from deep inside the handler.
    """


def require_fields(payload, *names, method: str = ""):
    """Validate that `payload` is a map carrying every field in `names`.

    Returns the payload so handlers can write
    `payload = require_fields(payload, "node_id", method="Heartbeat")`
    as their first line. graftlint R5 treats fields named here as
    validated; unvalidated subscripts of the request payload are
    flagged.
    """
    where = f" in {method}" if method else ""
    if not isinstance(payload, dict):
        raise MalformedError(
            f"Malformed request{where}: payload must be a map, "
            f"got {type(payload).__name__}")
    missing = [n for n in names if n not in payload]
    if missing:
        raise MalformedError(
            f"Malformed request{where}: missing field(s) "
            f"{', '.join(missing)}")
    return payload


def _maybe_attach_daemon_profiler(name: str) -> None:
    """Env-gated daemon CPU profiler: RAY_TPU_DAEMON_PROFILE=<dir> starts
    cProfile at boot; SIGUSR2 dumps `<dir>/<name>-<pid>.pstats` (daemons
    die by SIGKILL, so atexit can't be the dump trigger). Reference
    analog: RAY_PROFILING + py-spy hooks in the dashboard reporter."""
    import os

    out_dir = os.environ.get("RAY_TPU_DAEMON_PROFILE")
    if not out_dir:
        return
    import cProfile
    import signal

    prof = cProfile.Profile()
    prof.enable()

    def dump(signum, frame):
        prof.disable()
        path = os.path.join(out_dir, f"{name}-{os.getpid()}.pstats")
        try:
            os.makedirs(out_dir, exist_ok=True)
            prof.dump_stats(path)
        finally:
            prof.enable()

    signal.signal(signal.SIGUSR2, dump)

# Well-known resource names. TPU is first-class: a node exposes `TPU` chips
# and slice-topology labels so gang placement can target ICI-connected hosts
# (reference only knows TPU via autodetect: python/ray/_private/accelerator.py:155).
CPU = "CPU"
GPU = "GPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"


def normalize_resources(res: dict[str, float] | None) -> dict[str, float]:
    out = {}
    for k, v in (res or {}).items():
        if v is None:
            continue
        v = float(v)
        if v < 0:
            raise ValueError(f"resource {k} must be >= 0, got {v}")
        if v > 0:
            out[k] = v
    return out


def resources_fit(available: dict[str, float], demand: dict[str, float]) -> bool:
    return all(available.get(k, 0.0) + RESOURCE_EPS >= v for k, v in demand.items())


def subtract_resources(available: dict[str, float], demand: dict[str, float]) -> None:
    for k, v in demand.items():
        available[k] = available.get(k, 0.0) - v


def add_resources(available: dict[str, float], demand: dict[str, float]) -> None:
    for k, v in demand.items():
        available[k] = available.get(k, 0.0) + v


@dataclass
class Address:
    """Network address of a worker/raylet/gcs endpoint."""

    host: str
    port: int
    worker_id: str = ""   # hex; empty for daemons
    node_id: str = ""     # hex

    def to_wire(self):
        return [self.host, self.port, self.worker_id, self.node_id]

    @classmethod
    def from_wire(cls, w):
        return cls(w[0], w[1], w[2], w[3])

    def key(self):
        return (self.host, self.port)


STREAMING_RETURNS = -1  # TaskSpec.num_returns sentinel: streaming generator


@dataclass
class TaskSpec:
    """Wire form of a task invocation (reference: TaskSpecification).

    func_key: GCS function-table key (functions are registered once per job
    and fetched by workers on first use — reference:
    python/ray/_private/function_manager.py).
    args: list of wire-args; each is ["v", meta, data] inline value or
    ["r", object_id, owner_addr] reference.
    """

    task_id: str                      # hex
    job_id: str
    name: str
    func_key: str
    args: list = field(default_factory=list)
    kwargs_keys: list = field(default_factory=list)  # last len(kwargs_keys) args are kwargs
    num_returns: int = 1
    resources: dict = field(default_factory=dict)
    max_retries: int = 3
    retry_exceptions: bool = False
    owner: list | None = None         # Address.to_wire()
    # actor fields
    actor_id: str = ""                # set for actor tasks
    actor_creation: bool = False
    actor_seq: int = -1               # per-caller ordering for actor tasks
    max_restarts: int = 0
    max_task_retries: int = 0
    # scheduling
    strategy: list | None = None      # e.g. ["spread"], ["node_affinity", node_id, soft]
    placement_group: str = ""         # pg id hex
    pg_bundle_index: int = -1
    runtime_env: dict | None = None
    # W3C traceparent of the submitting span (reference: tracing context
    # propagates inside the TaskSpec, tracing_helper.py).
    trace_ctx: str = ""
    # Actor creation only: how many tasks may execute concurrently on the
    # actor (reference: max_concurrency / async actors, fiber.h).
    max_concurrency: int = 1
    # "device": returned jax.Arrays stay pinned in the executing worker's
    # HBM (device object plane, _private/device_objects.py); only a small
    # descriptor travels the object path.
    tensor_transport: str = ""

    def to_wire(self):
        return [
            self.task_id, self.job_id, self.name, self.func_key, self.args,
            self.kwargs_keys, self.num_returns, self.resources, self.max_retries,
            self.retry_exceptions, self.owner, self.actor_id, self.actor_creation,
            self.actor_seq, self.max_restarts, self.max_task_retries, self.strategy,
            self.placement_group, self.pg_bundle_index, self.runtime_env,
            self.trace_ctx, self.max_concurrency, self.tensor_transport,
        ]

    @classmethod
    def from_wire(cls, w):
        return cls(*w)


@dataclass
class NodeInfo:
    node_id: str
    host: str
    raylet_port: int
    total_resources: dict
    available_resources: dict
    labels: dict = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    store_path: str = ""
    is_head: bool = False
    # Port of the node's native C++ object-transfer server (0 = none;
    # peers then fall back to the RPC chunk path).
    transfer_port: int = 0
    # Drain ladder (reference: autoscaler.proto DrainNode / rpc
    # DrainNodeReason):
    # ALIVE -> DRAINING (evacuation in progress) -> DRAINED (safe to kill)
    # -> DEAD. A DRAINED node's death is expected and must not trigger
    # recovery storms.
    state: str = "ALIVE"
    drain_reason: str = ""        # preemption | idle | manual
    drain_deadline_s: float = 0.0
    drain_stats: dict = field(default_factory=dict)
    # Suspicion rung (partition tolerance): connection loss marks a node
    # SUSPECT (excluded from new placement, like DRAINING); only a
    # heartbeat-timeout expiry promotes SUSPECT -> DEAD. A re-register
    # inside the grace window restores `pre_suspect_state` and bumps
    # `suspect_recoveries` — the flap was a non-event.
    suspect_since_s: float = 0.0      # wall clock, for display; 0 = not suspect
    pre_suspect_state: str = ""       # state to restore on reconnect
    suspect_recoveries: int = 0       # times this node flapped and came back

    def to_wire(self):
        return {
            "node_id": self.node_id,
            "host": self.host,
            "raylet_port": self.raylet_port,
            "total_resources": self.total_resources,
            "available_resources": self.available_resources,
            "labels": self.labels,
            "alive": self.alive,
            "store_path": self.store_path,
            "is_head": self.is_head,
            "transfer_port": self.transfer_port,
            "state": self.state,
            "drain_reason": self.drain_reason,
            "drain_deadline_s": self.drain_deadline_s,
            "drain_stats": self.drain_stats,
            "suspect_since_s": self.suspect_since_s,
            "suspect_recoveries": self.suspect_recoveries,
        }


def wait_for_drained(get_nodes, node_id: str, deadline_s: float, *,
                     poll_s: float = 0.2, slack_s: float = 10.0):
    """Poll `get_nodes()` (a callable returning node-table wire dicts)
    until `node_id` finishes its drain. ONE implementation for every
    wait-for-DRAINED caller (CLI, autoscaler monitor, cluster_utils) so
    they cannot disagree about what a finished drain looks like.

    Returns (outcome, node_wire | None) with outcome one of:
      "DRAINED" — evacuation completed (even if the node has since
                  died: a self-drained raylet exits right after);
      "DIED"    — dead before reaching DRAINED (evacuation failed);
      "GONE"    — node vanished from the table;
      "TIMEOUT" — still draining past deadline_s + slack_s;
      "ERROR"   — get_nodes itself failed.
    """
    deadline = time.monotonic() + deadline_s + slack_s
    me = None
    while time.monotonic() < deadline:
        try:
            nodes = get_nodes()
        except Exception:
            logger.warning("wait_for_drained(%s): node listing failed",
                           node_id[:8], exc_info=True)
            return "ERROR", me
        me = next((n for n in nodes if n["node_id"] == node_id), None)
        if me is None:
            return "GONE", None
        if me.get("state") == "DRAINED":
            return "DRAINED", me
        if not me.get("alive"):
            return "DIED", me
        time.sleep(poll_s)
    return "TIMEOUT", me
