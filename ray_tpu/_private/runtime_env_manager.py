"""Node-side runtime-environment provisioning with ref-counted caching.

Parity: the reference's per-node RuntimeEnvAgent
(reference: python/ray/runtime_env/ARCHITECTURE.md — create-or-get URIs,
cache across workers, ref-count per consumer, GC at zero refs;
python/ray/_private/runtime_env/{pip,working_dir,py_modules}.py;
raylet side src/ray/raylet/agent_manager.cc). Owned by the raylet: workers
call EnsureRuntimeEnv before activating an env, the raylet materializes
each URI once per node, and releases a job's references when the GCS
publishes the job's finish event.

URI kinds:
  pip://<hash>            isolated site-packages built by `pip install
                          --target` from a requirements list (offline:
                          honors RAY_TPU_PIP_ARGS, e.g. "--no-index
                          --find-links /wheels")
  gcskv://pkg/<hash>      zip archive stored in the GCS KV table (local
                          working_dir/py_modules dirs are packed+uploaded
                          at submission, the reference's working_dir
                          upload semantics)
  file://<abs path>.zip   zip archive on a shared filesystem
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import shutil
import subprocess
import sys
import zipfile

logger = logging.getLogger(__name__)

# Archives above this are rejected at pack time (reference default:
# 500 MiB upload cap for working_dir packages).
MAX_PACKAGE_BYTES = 200 * 1024 * 1024

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def package_local_dir(path: str) -> bytes:
    """Zip a local directory into a deterministic archive (sorted entries,
    zeroed timestamps) so equal trees hash equal."""
    import io

    buf = io.BytesIO()
    path = os.path.abspath(path)
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for f in sorted(files):
            full = os.path.join(root, f)
            entries.append((os.path.relpath(full, path), full))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            zi = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            zi.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as fh:
                zf.writestr(zi, fh.read())
    data = buf.getvalue()
    if len(data) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"packaged dir {path!r} is {len(data)} bytes "
            f"(cap {MAX_PACKAGE_BYTES}); exclude large files")
    return data


def package_uri_for(data: bytes) -> str:
    return "gcskv://pkg/" + hashlib.sha1(data).hexdigest()


def pip_uri_for(reqs: list[str]) -> str:
    blob = "\n".join(sorted(reqs)).encode()
    return "pip://" + hashlib.sha1(blob).hexdigest()


class RuntimeEnvManager:
    """Materializes runtime-env URIs on this node, once each, with
    per-job reference counting and GC at zero references."""

    def __init__(self, session_dir: str, kv_get=None):
        self.base = os.path.join(session_dir, "runtime_envs")
        os.makedirs(self.base, exist_ok=True)
        # kv_get: async callable (ns, key) -> bytes | None, used to fetch
        # gcskv:// packages (wired to the raylet's GCS connection).
        self._kv_get = kv_get
        self._uri_jobs: dict[str, set[str]] = {}   # uri -> referencing jobs
        self._locks: dict[str, asyncio.Lock] = {}
        self._ready: dict[str, str] = {}           # uri -> local path

    # ---------- public ----------

    async def ensure(self, env: dict, job_id: str) -> dict:
        """Materialize every provisioned part of `env` on this node.
        Returns {"pip_dir": path|None, "working_dir": path|None,
        "py_modules": [path, ...]} with URIs resolved to local dirs."""
        out = {"pip_dir": None, "working_dir": None, "py_modules": []}
        reqs = env.get("pip")
        if reqs:
            out["pip_dir"] = await self._ensure_uri(
                pip_uri_for(list(reqs)), job_id, pip_reqs=list(reqs))
        wd = env.get("working_dir")
        if wd and _is_uri(wd):
            out["working_dir"] = await self._ensure_uri(wd, job_id)
        for m in env.get("py_modules") or []:
            if _is_uri(m):
                out["py_modules"].append(await self._ensure_uri(m, job_id))
            else:
                out["py_modules"].append(m)
        return out

    def release_job(self, job_id: str) -> None:
        """Drop all of `job_id`'s references; GC URIs that hit zero
        (reference: URI deleted when no job/actor references remain)."""
        for uri, jobs in list(self._uri_jobs.items()):
            jobs.discard(job_id)
            if not jobs:
                del self._uri_jobs[uri]
                path = self._ready.pop(uri, None)
                # NOTE: the lock object is kept (bounded by distinct URIs
                # per session) — popping it could hand a second lock to a
                # concurrent ensure and race two creations on one dest.
                if path and os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                    logger.info("runtime_env GC: removed %s (%s)", uri, path)

    def uris_in_use(self) -> dict:
        return {uri: sorted(jobs) for uri, jobs in self._uri_jobs.items()}

    # ---------- materialization ----------

    async def _ensure_uri(self, uri: str, job_id: str,
                          pip_reqs: list | None = None) -> str:
        # Job ref registered BEFORE creation so a concurrent release of
        # another job can never see an empty ref set mid-create; rolled
        # back if creation fails (no phantom in-use URIs).
        self._uri_jobs.setdefault(uri, set()).add(job_id)
        lock = self._locks.setdefault(uri, asyncio.Lock())
        try:
            async with lock:
                path = self._ready.get(uri)
                if path and os.path.isdir(path):
                    return path
                path = await self._create(uri, pip_reqs)
                self._ready[uri] = path
                return path
        except BaseException:
            jobs = self._uri_jobs.get(uri)
            if jobs is not None:
                jobs.discard(job_id)
                if not jobs:
                    del self._uri_jobs[uri]
            raise

    async def _create(self, uri: str, pip_reqs: list | None) -> str:
        h = hashlib.sha1(uri.encode()).hexdigest()[:16]
        if uri.startswith("pip://"):
            dest = os.path.join(self.base, f"pip-{h}")
            await asyncio.get_running_loop().run_in_executor(
                None, self._pip_install, pip_reqs or [], dest)
            return dest
        dest = os.path.join(self.base, f"pkg-{h}")
        data = await self._fetch_package(uri)
        tmp = dest + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        import io

        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        shutil.rmtree(dest, ignore_errors=True)
        os.replace(tmp, dest)
        return dest

    async def _fetch_package(self, uri: str) -> bytes:
        if uri.startswith("gcskv://"):
            ns, key = uri[len("gcskv://"):].split("/", 1)
            if self._kv_get is None:
                raise RuntimeError("no KV access for gcskv:// packages")
            data = await self._kv_get(ns, key)
            if data is None:
                raise FileNotFoundError(f"package {uri} not found in GCS KV")
            return data
        if uri.startswith("file://"):
            with open(uri[len("file://"):], "rb") as f:
                return f.read()
        if uri.endswith(".zip"):  # bare local archive path
            with open(uri, "rb") as f:
                return f.read()
        raise ValueError(f"unsupported runtime_env URI {uri!r}")

    def _pip_install(self, reqs: list[str], dest: str) -> None:
        """Isolated site-packages via `pip install --target` (reference:
        _private/runtime_env/pip.py builds a virtualenv; a --target dir is
        the TPU-image-friendly equivalent — no venv binaries, zero global
        state). Extra args (e.g. --no-index --find-links for the
        zero-egress test environment) come from RAY_TPU_PIP_ARGS."""
        tmp = dest + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        cmd = [sys.executable, "-m", "pip", "install", "--quiet",
               "--disable-pip-version-check", "--no-warn-script-location",
               "--target", tmp]
        cmd += os.environ.get("RAY_TPU_PIP_ARGS", "").split()
        cmd += list(reqs)
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
        if r.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"pip env creation failed rc={r.returncode}: "
                f"{r.stderr[-1000:]}")
        shutil.rmtree(dest, ignore_errors=True)
        os.replace(tmp, dest)


def _is_uri(s: str) -> bool:
    return s.startswith(("gcskv://", "file://")) or s.endswith(".zip")
