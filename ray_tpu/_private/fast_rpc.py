"""Daemon-side RPC server over the native fastpath pump.

Drop-in replacement for rpc.RpcServer (same wire protocol, same handler
signature `handler(conn, payload)`) whose IO plane is src/fastpath.cc:
accept, 4-byte-BE msgpack framing, read buffering, and writev-coalesced
sends all happen on one native epoll thread. The asyncio loop touches the
path once per *batch* (eventfd add_reader → fpump_drain), not once per
frame, and responses go out with a single non-blocking fpump_send — no
StreamWriter, no per-frame drain() hop.

This is the round-5 step of moving the daemons (raylet, GCS) onto the
native pump (reference analog: gcs_server.h:79 and node_manager.cc:1778
run on C++ gRPC/asio event loops end-to-end). Python keeps the protocol
logic; every syscall on the lease/return/pin and GCS-table paths is
native.

Sync handlers (plain functions) complete inline in the drain callback —
no task spawn per request. Async handlers are scheduled exactly like
rpc.Connection._dispatch would.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import traceback
from typing import Awaitable, Callable

from ray_tpu._private import rpc
from ray_tpu._private.common import supervised_task
from ray_tpu._private.event_stats import EventLoopStats
from ray_tpu._private.native_fastpath import (EV_ACCEPT, EV_CLOSE, EV_FRAME,
                                              EV_INJECT)
from ray_tpu._private.rpc import (MSG_ERROR, MSG_NOTIFY, MSG_REQUEST,
                                  MSG_RESPONSE, ConnectionLost, RpcError,
                                  pack, unpack)

logger = logging.getLogger(__name__)


class FastConn:
    """Server side of one accepted pump connection.

    Interface-compatible with the subset of rpc.Connection the daemons
    use on accepted conns: call/notify/on_close/closed/handlers/peername.
    """

    def __init__(self, server: "FastRpcServer", conn_id: int):
        self._server = server
        self._conn_id = conn_id
        self.handlers = server.handlers  # shared, like RpcServer accepts
        self.name = f"{server.name}-peer{conn_id}"
        self._seq = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._close_callbacks: list[Callable[[], None]] = []

    @property
    def closed(self) -> bool:
        return self._closed

    def peername(self):
        return None  # the pump doesn't surface peer addresses

    def on_close(self, cb: Callable[[], None]) -> None:
        self._close_callbacks.append(cb)

    def _send_frame(self, frame: list) -> bool:
        return self._server._send(self._conn_id, frame)

    async def call(self, method: str, payload=None,
                   timeout: float | None = None):
        if self._closed:
            raise ConnectionLost(f"{self.name}: connection closed")
        self._seq += 1
        seq = self._seq
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        try:
            if not self._send_frame([MSG_REQUEST, seq, method, payload]):
                raise ConnectionLost(f"{self.name}: send failed")
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(seq, None)

    async def notify(self, method: str, payload=None) -> None:
        if self._closed:
            raise ConnectionLost(f"{self.name}: connection closed")
        if not self._send_frame([MSG_NOTIFY, 0, method, payload]):
            raise ConnectionLost(f"{self.name}: send failed")

    async def close(self) -> None:
        self._server._close_conn(self._conn_id)

    def _shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                try:
                    fut.set_exception(
                        ConnectionLost(f"{self.name}: connection lost"))
                except RuntimeError:
                    pass
        self._pending.clear()
        for cb in self._close_callbacks:
            try:
                cb()
            except Exception:
                logger.exception("close callback failed")


class FastRpcServer:
    """RpcServer-compatible daemon server on the native frame pump."""

    def __init__(self, handlers: dict[str, Callable], name: str = "server",
                 on_connect: Callable | None = None):
        self.handlers = handlers
        self.name = name
        self.on_connect = on_connect
        self.connections: set[FastConn] = set()
        self.port: int | None = None
        self.host: str | None = None
        # Optional in-pump native service (daemon protocol logic in C++,
        # e.g. the GCS KV/pubsub handlers — src/gcs_service.cc): a
        # callable(pump) -> service|None installed by the daemon BEFORE
        # start(); it runs between pump creation and listen() so the
        # loop thread sees the hook before any frame arrives.
        self.service_factory = None
        self.native_service = None
        # EV_INJECT consumer: callable(token, body) for events a native
        # service pushes into the pump FIFO (fpump_inject) to mirror
        # natively-handled control decisions back into Python state.
        self.inject_handler = None
        # Per-handler dispatch latency + drain batch stats (analogue of
        # the reference's event_stats.h around its asio loop posts).
        self.stats = EventLoopStats(name)
        self._pump = None
        self._conns: dict[int, FastConn] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stopped = False
        self._inflight: set = set()  # strong refs to in-flight dispatches

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu._private import native_fastpath

        pump = native_fastpath.FastPump()
        if self.service_factory is not None:
            self.native_service = self.service_factory(pump)
        # port=0 picks an ephemeral port; a fixed port (GCS
        # restart-on-same-port) binds with SO_REUSEADDR.
        self.port = pump.listen(host, port)
        self.host = host
        self._pump = pump
        self._loop = asyncio.get_running_loop()
        pump.arm_eventfd(True)
        self._loop.add_reader(pump.eventfd, self._on_events)
        return self.host, self.port

    # ---- event plumbing ----

    def _on_events(self) -> None:
        try:
            os.read(self._pump.eventfd, 8)
        except (OSError, ValueError):
            pass
        # A push racing this drain re-bumps the eventfd, so the reader
        # re-fires — but FastPump.drain() stops at max_events with the
        # rest STRANDED behind the already-zeroed fd (nothing re-bumps on
        # pop), so keep draining until a short batch proves the queue is
        # empty.
        while True:
            events = self._pump.drain(max_events=512)
            if events:
                self.stats.record_drain(len(events))
            for ev in events:
                self._handle_event(ev)
            if len(events) < 512:
                return

    def _handle_event(self, ev) -> None:
        kind, conn_id, body = ev
        if kind == EV_FRAME:
            conn = self._conns.get(conn_id)
            if conn is not None:
                self._on_frame(conn, body)
        elif kind == EV_ACCEPT:
            conn = FastConn(self, conn_id)
            self._conns[conn_id] = conn
            self.connections.add(conn)
            if self.on_connect:
                try:
                    self.on_connect(conn)
                except Exception:
                    logger.exception("%s: on_connect failed", self.name)
        elif kind == EV_CLOSE:
            conn = self._conns.pop(conn_id, None)
            if conn is not None:
                self.connections.discard(conn)
                conn._shutdown()
        elif kind == EV_INJECT:
            # conn_id slot carries the inject token, not a connection.
            if self.inject_handler is not None:
                try:
                    self.inject_handler(conn_id, body)
                except Exception:
                    logger.exception("%s: inject handler failed",
                                     self.name)

    def _on_frame(self, conn: FastConn, body: bytes) -> None:
        try:
            msg_type, seq, method, payload = unpack(body)
        except Exception:
            logger.exception("%s: bad frame", self.name)
            return
        if msg_type == MSG_REQUEST:
            self._dispatch(conn, seq, method, payload)
        elif msg_type == MSG_NOTIFY:
            self._dispatch(conn, None, method, payload)
        elif msg_type in (MSG_RESPONSE, MSG_ERROR):
            fut = conn._pending.get(seq)
            if fut is not None and not fut.done():
                if msg_type == MSG_RESPONSE:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RpcError(payload))

    def _dispatch(self, conn: FastConn, seq, method: str, payload) -> None:
        handler = conn.handlers.get(method)
        t0 = time.perf_counter()
        record = None
        if isinstance(payload, dict) and rpc._SID_KEY in payload:
            # Session-stamped request: consult the shared reply cache so
            # a replayed mutating RPC answers from cache instead of
            # executing twice (see rpc.SessionManager).
            def _dup_reply(kind, value, _cid=conn._conn_id, _seq=seq,
                           _method=method):
                self._send(_cid, [kind, _seq, _method, value])

            execute, record, payload = rpc._session_intercept(
                payload, seq, _dup_reply)
            if not execute:
                return
        try:
            if handler is None:
                raise RpcError(f"no handler for {method!r}")
            result = handler(conn, payload)
        except Exception as e:
            self.stats.record_handler(method, time.perf_counter() - t0,
                                      error=True)
            self._reply_error(conn, seq, method, e, record)
            return
        if isinstance(result, Awaitable):
            # supervised_task holds the strong ref in _inflight (raw
            # create_task keeps only a weak one) and logs any exception
            # that escapes _finish's own handling.
            supervised_task(
                self._finish(conn, seq, method, result, t0, record),
                name=f"dispatch-{method}", tasks=self._inflight)
            self.stats.set_queue_depth(len(self._inflight))
        else:
            self.stats.record_handler(method, time.perf_counter() - t0)
            if record is not None:
                result = rpc._stamp_reply(result)
                record(MSG_RESPONSE, result)
            if seq is not None:
                self._send(conn._conn_id,
                           [MSG_RESPONSE, seq, method, result])

    async def _finish(self, conn: FastConn, seq, method: str, coro,
                      t0: float, record=None) -> None:
        try:
            result = await coro
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.stats.record_handler(method, time.perf_counter() - t0,
                                      error=True)
            self._reply_error(conn, seq, method, e, record)
            return
        finally:
            self.stats.set_queue_depth(max(0, len(self._inflight) - 1))
        self.stats.record_handler(method, time.perf_counter() - t0)
        if record is not None:
            result = rpc._stamp_reply(result)
            record(MSG_RESPONSE, result)
        if seq is not None:
            self._send(conn._conn_id, [MSG_RESPONSE, seq, method, result])

    def _reply_error(self, conn: FastConn, seq, method: str, e: Exception,
                     record=None):
        err = f"{e}\n{traceback.format_exc()}"
        if record is not None:
            record(MSG_ERROR, err)
        if seq is not None:
            self._send(conn._conn_id, [MSG_ERROR, seq, method, err])
        else:
            logger.error("%s: error in notify handler %s: %s",
                         self.name, method, e)

    def _send(self, conn_id: int, frame: list) -> bool:
        if self._pump is None:
            return False
        return self._pump.send(conn_id, pack(frame))

    def _close_conn(self, conn_id: int) -> None:
        conn = self._conns.pop(conn_id, None)
        if conn is not None:
            self.connections.discard(conn)
            conn._shutdown()
        if self._pump is not None:
            self._pump.close_conn(conn_id)

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._loop is not None and self._pump is not None:
            try:
                self._loop.remove_reader(self._pump.eventfd)
            except Exception:
                pass
        # In-flight async dispatches would otherwise keep running against
        # torn-down state and surface as pending-task noise at loop close.
        for task in list(self._inflight):
            task.cancel()
        if self._inflight:
            await asyncio.wait(list(self._inflight), timeout=2)
        self._inflight.clear()
        for conn in list(self.connections):
            conn._shutdown()
        self.connections.clear()
        self._conns.clear()
        if self._pump is not None:
            self._pump.close()
            self._pump = None
        # Destroy the native service only after the pump loop thread is
        # joined (close() above) — it must never run the frame hook
        # against a freed service.
        if self.native_service is not None:
            self.native_service.close()
            self.native_service = None


def make_server(handlers: dict[str, Callable], name: str = "server",
                on_connect: Callable | None = None):
    """Return a FastRpcServer when the native pump is available, else the
    asyncio RpcServer — daemons call this and stay agnostic."""
    from ray_tpu._private import native_fastpath

    if native_fastpath.available() and \
            os.environ.get("RAY_TPU_DAEMON_FASTPATH", "1") not in (
                "0", "false", "no"):
        return FastRpcServer(handlers, name=name, on_connect=on_connect)
    return rpc.RpcServer(handlers, name=name, on_connect=on_connect)
