"""ctypes binding for the native in-pump GCS service (src/gcs_service.cc).

The first slice of daemon PROTOCOL logic in C++: the GCS's namespaced KV
table (KVPut/KVGet/KVDel/KVKeys/KVExists) and pubsub (Subscribe/Publish +
fanout) execute entirely on the fastpath pump's epoll thread — parse,
mutate, WAL write-through, response pack, send — without ever crossing
into Python (reference analog: gcs_kv_manager.cc / pubsub_handler.cc
dispatched on the gcs_server C++ event loop, gcs_server.h:79).

The service is wired by ADDRESS: it receives fpump_send / gstore_put /
gstore_del entry points and the pump/store handles as plain pointers, so
libtpugsvc.so stays self-contained (no cross-.so linking games).
"""

from __future__ import annotations

import ctypes
import os
import threading

from ray_tpu._private.native_build import ensure_built

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = ensure_built("gcs_service.cc", "libtpugsvc.so",
                            dep_names=("msgpack_lite.h",))
        lib = ctypes.CDLL(path)
        lib.gsvc_create.restype = ctypes.c_void_p
        lib.gsvc_create.argtypes = [ctypes.c_void_p] * 5
        lib.gsvc_destroy.argtypes = [ctypes.c_void_p]
        lib.gsvc_kv_load.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.gsvc_fanout.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int, ctypes.c_char_p,
                                    ctypes.c_uint32]
        lib.gsvc_fanout.restype = ctypes.c_int
        lib.gsvc_sub_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int]
        lib.gsvc_sub_count.restype = ctypes.c_int
        lib.gsvc_kv_stats.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.POINTER(ctypes.c_int64)]
        lib.gsvc_counters.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.POINTER(ctypes.c_uint64)]
        lib.gsvc_proto_errors.argtypes = [ctypes.c_void_p]
        lib.gsvc_proto_errors.restype = ctypes.c_uint64
        # gsvc_on_frame / gsvc_on_close are only ever CALLED by the pump
        # loop thread; Python just needs their addresses for
        # fpump_set_service.
        _lib = lib
        return lib


def available() -> bool:
    if os.environ.get("RAY_TPU_NATIVE_GCS_SERVICE", "1") in (
            "0", "false", "no"):
        return False
    try:
        _load()
        return True
    except Exception:
        return False


def _addr(fn) -> int:
    return ctypes.cast(fn, ctypes.c_void_p).value


class GcsNativeService:
    """Owns one native service instance, installed into a FastPump."""

    def __init__(self, pump, store=None):
        """pump: native_fastpath.FastPump (pre-listen).
        store: native_gcs_store.GcsTableStore or None (no persistence).

        Construction does NOT install the pump hook — call install()
        after any restore-time kv_load calls succeed, so a failed
        restore can fall back to the Python handlers without leaving a
        half-loaded native service answering frames."""
        lib = _load()
        self._lib = lib
        self._pump = pump
        from ray_tpu._private import native_fastpath

        fplib = native_fastpath._load()
        if store is not None:
            put_addr = _addr(store._lib.gstore_put)
            del_addr = _addr(store._lib.gstore_del)
            store_h = store._h
        else:
            put_addr = del_addr = store_h = None
        self._h = ctypes.c_void_p(lib.gsvc_create(
            _addr(fplib.fpump_send), pump._h, put_addr, del_addr, store_h))
        if not self._h:
            raise OSError("gsvc_create failed")

    def frame_addr(self) -> int:
        return _addr(self._lib.gsvc_on_frame)

    def close_addr(self) -> int:
        return _addr(self._lib.gsvc_on_close)

    def install(self) -> None:
        """Point the pump's in-loop hook at this service (pre-listen)."""
        self._pump.set_service(self.frame_addr(), self.close_addr(),
                               self._h)

    def close(self) -> None:
        if self._h:
            self._lib.gsvc_destroy(self._h)
            self._h = None

    def kv_load(self, ns: str, key_slice: bytes, val_slice: bytes) -> None:
        if not self._h:
            return
        nsb = ns.encode()
        self._lib.gsvc_kv_load(self._h, nsb, len(nsb), key_slice,
                               len(key_slice), val_slice, len(val_slice))

    def fanout(self, channel: str, frame: bytes) -> int:
        if not self._h:
            return 0
        ch = channel.encode()
        return self._lib.gsvc_fanout(self._h, ch, len(ch), frame,
                                     len(frame))

    def sub_count(self, channel: str) -> int:
        if not self._h:
            return 0
        ch = channel.encode()
        return self._lib.gsvc_sub_count(self._h, ch, len(ch))

    def kv_stats(self) -> tuple[int, int]:
        if not self._h:
            return 0, 0
        n_ns = ctypes.c_int64()
        n_rows = ctypes.c_int64()
        self._lib.gsvc_kv_stats(self._h, ctypes.byref(n_ns),
                                ctypes.byref(n_rows))
        return n_ns.value, n_rows.value

    def proto_errors(self) -> int:
        if not self._h:
            return 0
        return self._lib.gsvc_proto_errors(self._h)

    def counters(self) -> tuple[int, int, int]:
        """(frames handled natively, WAL appends, WAL failures)."""
        if not self._h:
            return 0, 0, 0
        handled = ctypes.c_uint64()
        appends = ctypes.c_uint64()
        failures = ctypes.c_uint64()
        self._lib.gsvc_counters(self._h, ctypes.byref(handled),
                                ctypes.byref(appends),
                                ctypes.byref(failures))
        return handled.value, appends.value, failures.value
