"""ObjectRef / RemoteFunction / ActorClass plumbing behind the public API.

Parity: reference python/ray/_private/worker.py (global worker),
remote_function.py:257 (_remote), actor.py (ActorClass/ActorHandle/
ActorMethod), _private/ray_option_utils.py (options validation).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any

from ray_tpu import exceptions as exc
from ray_tpu._private.common import Address, TaskSpec, normalize_resources
from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu.util import tracing

_core_worker = None
_lock = threading.RLock()


def set_core_worker(cw) -> None:
    global _core_worker
    with _lock:
        _core_worker = cw


def get_core_worker():
    if _core_worker is None:
        raise exc.RayTpuError(
            "ray_tpu is not initialized; call ray_tpu.init() first")
    return _core_worker


def _client_fallback():
    """Active ClientContext when this process has no CoreWorker, else None."""
    import ray_tpu

    return ray_tpu._client_mode()


def core_worker_or_none():
    return _core_worker


_nested_ctx = threading.local()


@contextlib.contextmanager
def collect_nested_refs():
    """Serialize-side collector: while active, ObjectRef.__reduce__ appends
    (oid_hex, owner_wire) here instead of job-lifetime pinning — the caller
    then applies borrower-protocol accounting to the collected refs
    (reference: reference_count.cc tracks refs found while serializing
    arguments/returns)."""
    prev = getattr(_nested_ctx, "ser_sink", None)
    sink: list = []
    _nested_ctx.ser_sink = sink
    try:
        yield sink
    finally:
        _nested_ctx.ser_sink = prev


@contextlib.contextmanager
def deser_context(preregistered: set | None = None):
    """Deserialize-side collector: rebuilt borrowed refs are recorded here;
    `preregistered` oids are ones the payload's sender already registered
    with their owners on our behalf (no BorrowRef needed from us)."""
    prev = (getattr(_nested_ctx, "deser_sink", None),
            getattr(_nested_ctx, "deser_prereg", None))
    sink: list = []
    _nested_ctx.deser_sink = sink
    _nested_ctx.deser_prereg = preregistered or set()
    try:
        yield sink
    finally:
        _nested_ctx.deser_sink, _nested_ctx.deser_prereg = prev


class ObjectRef:
    """A reference to an object owned by some worker (reference:
    python/ray ObjectRef; owner address travels with the ref as in
    src/ray/protobuf/common.proto ObjectReference)."""

    __slots__ = ("id", "owner", "_registered", "_borrowed")

    def __init__(self, oid: ObjectID, owner: Address | None, _register: bool = True):
        self.id = oid
        self.owner = owner
        self._registered = False
        self._borrowed = False
        cw = _core_worker
        if _register and cw is not None:
            cw.add_local_ref(oid.hex())
            self._registered = True

    def hex(self) -> str:
        return self.id.hex()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        cw = _core_worker
        if cw is None:
            return
        try:
            if self._registered:
                cw.remove_local_ref(self.id.hex())
            elif self._borrowed:
                cw.borrow_decr(self.id.hex())
        except Exception:
            pass

    def __reduce__(self):
        # Nested-ref serialization (ref inside a value arg / return / put
        # payload). Inside a runtime serialization context the ref is
        # COLLECTED and handled by the borrower protocol
        # (reference: reference_count.cc). A bare out-of-band pickle (user
        # calling pickle.dumps directly) falls back to the job-lifetime
        # owner pin, the only safe default without a recipient to track.
        sink = getattr(_nested_ctx, "ser_sink", None)
        owner_wire = self.owner.to_wire() if self.owner else None
        if sink is not None:
            sink.append((self.id.hex(), owner_wire))
        else:
            cw = _core_worker
            if cw is not None and self.owner is not None \
                    and self.owner.worker_id == cw.worker_id:
                cw.pin_nested_ref(self.id.hex())
        # type(self): a DeviceObjectRef must survive the pickle hop as
        # one (isinstance routing on the receiver would silently break).
        return (_rebuild_object_ref,
                (self.id.binary(), owner_wire, type(self)))

    # Allow `await ref` patterns later; for now block via global get.
    def future(self):
        raise NotImplementedError


class DeviceObjectRef(ObjectRef):
    """Reference to an HBM-resident object (device object plane,
    _private/device_objects.py): the payload stays pinned on the
    producing worker; only a descriptor travels the object path. Flows
    through task args and ray_tpu.get like any ObjectRef — resolution
    picks the cheapest transfer route."""

    __slots__ = ()

    def __repr__(self):
        return f"DeviceObjectRef({self.id.hex()})"


def _rebuild_object_ref(id_bytes, owner_wire, ref_cls=None):
    owner = Address.from_wire(owner_wire) if owner_wire else None
    ref = (ref_cls or ObjectRef)(ObjectID(id_bytes), owner, _register=False)
    cw = _core_worker
    if cw is None or owner is None:
        return ref
    oid_hex = ref.id.hex()
    if owner.worker_id == cw.worker_id:
        # Deserializing our own ref: count it like any locally created
        # handle so user-held copies keep the object alive.
        cw.add_local_ref(oid_hex)
        ref._registered = True
        return ref
    # Borrowed ref: one local count per live handle (reference:
    # reference_count.cc borrower accounting).
    prereg = getattr(_nested_ctx, "deser_prereg", None)
    cw.borrow_incr(oid_hex, owner,
                   registered=bool(prereg and oid_hex in prereg))
    ref._borrowed = True
    sink = getattr(_nested_ctx, "deser_sink", None)
    if sink is not None:
        sink.append((oid_hex, owner))
    return ref


_OPTION_DEFAULTS = {
    "num_cpus": None,
    "num_gpus": None,
    "num_tpus": None,
    "resources": None,
    "num_returns": 1,
    "max_retries": 3,
    "retry_exceptions": False,
    "name": None,
    "max_restarts": 0,
    "max_task_retries": 0,
    "max_concurrency": 1,
    "scheduling_strategy": None,
    "placement_group": None,
    "placement_group_bundle_index": -1,
    "lifetime": None,
    "namespace": None,
    "get_if_exists": False,
    "runtime_env": None,
    "memory": None,
    "accelerator_type": None,
    "tensor_transport": None,
}


def _validate_options(opts: dict, for_actor: bool) -> dict:
    out = dict(_OPTION_DEFAULTS)
    for k, v in opts.items():
        if k not in _OPTION_DEFAULTS:
            raise ValueError(f"unknown option {k!r}")
        out[k] = v
    if out["lifetime"] not in (None, "detached", "non_detached"):
        raise ValueError("lifetime must be None, 'detached', or 'non_detached'")
    if out["tensor_transport"] not in (None, "object_store", "device"):
        raise ValueError("tensor_transport must be None, 'object_store', "
                         "or 'device'")
    if not for_actor and out["max_restarts"]:
        raise ValueError("max_restarts is an actor option")
    return out


def _build_resources(opts: dict, default_cpus: float) -> dict:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = opts["num_cpus"]
    elif "CPU" not in res:
        res["CPU"] = default_cpus
    if opts.get("num_gpus") is not None:
        res["GPU"] = opts["num_gpus"]
    if opts.get("num_tpus") is not None:
        res["TPU"] = opts["num_tpus"]
    if opts.get("memory") is not None:
        res["memory"] = opts["memory"]
    if opts.get("accelerator_type"):
        res[f"accelerator_type:{opts['accelerator_type']}"] = 0.001
    return normalize_resources(res)


_runtime_env_mod = None


def _effective_runtime_env(task_env: dict | None) -> dict | None:
    """Task env merged over the job-level default (reference semantics:
    job runtime_env inherited unless the task overrides per-field), with
    local working_dir/py_modules dirs packed + uploaded to the GCS KV as
    content-addressed packages (reference: working_dir upload)."""
    global _runtime_env_mod
    if _runtime_env_mod is None:
        from ray_tpu import runtime_env as _runtime_env_mod_  # cycle-free
        _runtime_env_mod = _runtime_env_mod_
    m = _runtime_env_mod
    if task_env is None and m.get_job_runtime_env() is None:
        return None  # hot path: no env anywhere, skip merge machinery
    return m.prepare_for_wire(
        m.RuntimeEnv.merge(m.get_job_runtime_env(), task_env))


def _wire_strategy(opts: dict):
    """Convert a SchedulingStrategy option to wire form."""
    strategy = opts.get("scheduling_strategy")
    pg_id = ""
    bundle_index = opts.get("placement_group_bundle_index", -1)
    if opts.get("placement_group") is not None:
        pg = opts["placement_group"]
        pg_id = pg.id.hex() if hasattr(pg, "id") else str(pg)
    if strategy is None:
        return None, pg_id, bundle_index
    if isinstance(strategy, str):
        if strategy == "SPREAD":
            return ["spread"], pg_id, bundle_index
        if strategy == "DEFAULT":
            return None, pg_id, bundle_index
        raise ValueError(f"unknown scheduling strategy {strategy!r}")
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return ["node_affinity", strategy.node_id, strategy.soft], pg_id, bundle_index
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        return None, pg.id.hex(), strategy.placement_group_bundle_index
    raise ValueError(f"unsupported scheduling strategy {strategy!r}")


class RemoteFunction:
    def __init__(self, fn, opts: dict):
        self._fn = fn
        self._opts = _validate_options(opts, for_actor=False)
        # Registration cache keyed per job: decorated module-level functions
        # outlive clusters (tests start many), so one cached key would point
        # at a GCS that no longer exists.
        self._func_keys: dict[str, str] = {}
        self._wire_cache = None  # (strategy triple, resources) per-opts
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        merged = {k: v for k, v in self._opts.items() if v != _OPTION_DEFAULTS[k]}
        merged.update(opts)
        rf = RemoteFunction(self._fn, merged)
        rf._func_keys = self._func_keys
        return rf

    def remote(self, *args, **kwargs):
        ctx = _client_fallback()
        if ctx is not None:
            # Decorated before init(address="client://..."): route through
            # the client context at call time (reference: client_mode_hook).
            # Cache keyed by context — a reconnect gets a fresh wrapper.
            cached = getattr(self, "_client_rf", None)
            if cached is None or cached[0] is not ctx:
                cached = self._client_rf = (ctx, ctx.remote(self._fn, self._opts))
            return cached[1].remote(*args, **kwargs)
        cw = get_core_worker()
        func_key = self._func_keys.get(cw.job_id)
        if func_key is None:
            func_key = self._func_keys[cw.job_id] = cw.register_function(self._fn)
        wire_args, kwargs_keys, _deps, nested = cw.serialize_args(args, kwargs)
        # Options are immutable per RemoteFunction (options() returns a new
        # instance): derive strategy/resources once, not per .remote().
        cached = self._wire_cache
        if cached is None:
            cached = self._wire_cache = (
                _wire_strategy(self._opts),
                _build_resources(self._opts, default_cpus=1.0))
        (strategy, pg_id, bundle_index), resources = cached
        task_id = cw.next_task_id()
        streaming = self._opts["num_returns"] in ("streaming", "dynamic")
        transport = self._opts["tensor_transport"]
        spec = TaskSpec(
            task_id=task_id.hex(),
            job_id=cw.job_id,
            name=self._opts["name"] or getattr(self._fn, "__name__", "anonymous"),
            func_key=func_key,
            args=wire_args,
            kwargs_keys=kwargs_keys,
            num_returns=-1 if streaming else self._opts["num_returns"],
            resources=dict(resources),  # spec owns a private copy
            # Streaming tasks retry like plain tasks: a retried
            # generator re-executes from scratch and the owner
            # fast-forwards already-delivered yields by index
            # (reference: generator retry semantics in task_manager.cc
            # HandleReportGeneratorItemReturns).
            max_retries=self._opts["max_retries"],
            retry_exceptions=bool(self._opts["retry_exceptions"]),
            owner=cw.address.to_wire(),
            strategy=strategy,
            placement_group=pg_id,
            pg_bundle_index=bundle_index,
            runtime_env=_effective_runtime_env(self._opts["runtime_env"]),
            tensor_transport=transport if transport == "device" else "",
        )
        submit = cw.submit_streaming_task if streaming else cw.submit_task
        if tracing.enabled():
            with tracing.submit_span(spec.name, spec.task_id) as trace_ctx:
                spec.trace_ctx = trace_ctx
                out = submit(spec, nested_args=nested, task_id=task_id)
        else:  # hot path: skip two contextmanager frames per task
            out = submit(spec, nested_args=nested, task_id=task_id)
        if streaming:
            return ObjectRefGenerator(spec.task_id, cw.address, out)
        ref_cls = DeviceObjectRef if transport == "device" else ObjectRef
        refs = [ref_cls(oid, cw.address) for oid in out]
        if self._opts["num_returns"] == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__!r} cannot be called directly; "
            f"use .remote()")


class ObjectRefGenerator:
    """Iterator of ObjectRefs from a num_returns="streaming" task
    (reference: ray ObjectRefGenerator / DynamicObjectRefGenerator).
    Items arrive as the remote generator yields; iteration blocks until
    the next item, raises the task's error at the failure point, and
    stops when the task completes."""

    def __init__(self, task_id_hex: str, owner, queue):
        self._task_id = task_id_hex
        self._owner = owner
        self._q = queue
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        if self._done:
            raise StopIteration
        item = self._q.get()
        if item[0] == "item":
            from ray_tpu._private.ids import ObjectID

            return ObjectRef(ObjectID.from_hex(item[1]), self._owner)
        self._done = True
        cw = _core_worker
        if cw is not None:
            # Exhausted/failed: nothing buffered left to free — drop the
            # owner-side stream bookkeeping now (close() after this is a
            # no-op thanks to _done).
            cw.stream_finished(self._task_id)
        if item[0] == "end":
            raise StopIteration
        from ray_tpu import exceptions as _exc
        from ray_tpu._private import serialization

        kind, value = serialization.deserialize(bytes(item[1]),
                                                bytes(item[2]))
        if kind == serialization.KIND_EXCEPTION:
            cause, tb = value
            if isinstance(cause, _exc.RayTpuError):
                raise cause
            raise _exc.TaskError(cause, tb)
        raise RuntimeError(str(value))

    def completed(self) -> bool:
        return self._done

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        """`async for ref in gen` — the blocking queue wait offloads to
        a thread so the event loop stays free (reference: ObjectRef
        generators are async-iterable inside async actors)."""
        import asyncio

        _end = object()

        def step():
            # StopIteration cannot cross a Future boundary; sentinel it.
            try:
                return self.__next__()
            except StopIteration:
                return _end

        out = await asyncio.to_thread(step)
        if out is _end:
            raise StopAsyncIteration
        return out

    def close(self) -> None:
        """Release unconsumed yields (reference: Ray frees unconsumed
        generator returns when the generator is destructed). The core
        worker's IO loop drains buffered items and frees later arrivals
        — draining here would race an in-flight yield dispatch."""
        if self._done:
            return
        self._done = True
        cw = _core_worker
        if cw is not None:
            cw.abandon_stream(self._task_id)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, tensor_transport: str | None = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._tensor_transport = tensor_transport

    def options(self, **opts):
        bad = set(opts) - {"num_returns", "tensor_transport"}
        if bad:
            raise ValueError(f"unknown actor-method options {sorted(bad)}")
        n = opts.get("num_returns", self._num_returns)
        tt = opts.get("tensor_transport", self._tensor_transport)
        if tt not in (None, "object_store", "device"):
            raise ValueError("tensor_transport must be None, "
                             "'object_store', or 'device'")
        return ActorMethod(self._handle, self._method_name, n, tt)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._num_returns,
            tensor_transport=self._tensor_transport)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._method_name!r} cannot be called directly; "
            f"use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 max_task_retries: int = 0,
                 tensor_transport: str | None = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries
        # Class-level @ray_tpu.remote(tensor_transport=...) default;
        # per-method .options(tensor_transport=...) overrides.
        self._tensor_transport = tensor_transport

    @property
    def _id_hex(self) -> str:
        return self._actor_id.hex()

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name,
                           tensor_transport=self._tensor_transport)

    def _submit_method(self, method_name: str, args, kwargs, num_returns,
                       tensor_transport: str | None = None):
        cw = get_core_worker()
        streaming = num_returns in ("streaming", "dynamic")
        wire_args, kwargs_keys, _, nested = cw.serialize_args(args, kwargs)
        task_id = cw.next_task_id()
        spec = TaskSpec(
            task_id=task_id.hex(),
            job_id=cw.job_id,
            name=f"{self._class_name}.{method_name}",
            func_key="",
            args=wire_args,
            kwargs_keys=kwargs_keys,
            num_returns=-1 if streaming else num_returns,
            resources={},
            max_retries=0,
            owner=cw.address.to_wire(),
            actor_id=self._actor_id.hex(),
            tensor_transport=("device" if tensor_transport == "device"
                              else ""),
        )
        with tracing.submit_span(spec.name, spec.task_id) as trace_ctx:
            spec.trace_ctx = trace_ctx
            out = cw.submit_actor_task(self._actor_id.hex(), spec,
                                       self._max_task_retries,
                                       nested_args=nested)
        if streaming:
            return ObjectRefGenerator(spec.task_id, cw.address, out)
        ref_cls = (DeviceObjectRef if tensor_transport == "device"
                   else ObjectRef)
        refs = [ref_cls(oid, cw.address) for oid in out]
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (_rebuild_actor_handle,
                (self._actor_id.binary(), self._class_name,
                 self._max_task_retries, self._tensor_transport))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


def _rebuild_actor_handle(id_bytes, class_name, max_task_retries,
                          tensor_transport=None):
    return ActorHandle(ActorID(id_bytes), class_name, max_task_retries,
                       tensor_transport)


class ActorClass:
    def __init__(self, cls, opts: dict):
        self._cls = cls
        self._opts = _validate_options(opts, for_actor=True)
        self._class_keys: dict[str, str] = {}  # per-job, see RemoteFunction

    def options(self, **opts) -> "ActorClass":
        merged = {k: v for k, v in self._opts.items() if v != _OPTION_DEFAULTS[k]}
        merged.update(opts)
        ac = ActorClass(self._cls, merged)
        ac._class_keys = self._class_keys
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        ctx = _client_fallback()
        if ctx is not None:
            cached = getattr(self, "_client_ac", None)
            if cached is None or cached[0] is not ctx:
                cached = self._client_ac = (ctx, ctx.remote(self._cls, self._opts))
            return cached[1].remote(*args, **kwargs)
        cw = get_core_worker()
        class_key = self._class_keys.get(cw.job_id)
        if class_key is None:
            class_key = self._class_keys[cw.job_id] = cw.register_function(self._cls)
        actor_id = ActorID.from_random()
        # Constructor args are held for the actor's lifetime (the actor
        # may stash nested refs in self; released with the job).
        wire_args, kwargs_keys, _, _nested = cw.serialize_args(args, kwargs)
        strategy, pg_id, bundle_index = _wire_strategy(self._opts)
        task_id = cw.next_task_id()
        spec = TaskSpec(
            task_id=task_id.hex(),
            job_id=cw.job_id,
            name=f"{self._cls.__name__}.__init__",
            func_key=class_key,
            args=wire_args,
            kwargs_keys=kwargs_keys,
            num_returns=0,
            # Actors with no explicit resources hold 0 CPU while alive
            # (reference: python/ray/actor.py default num_cpus=0 for
            # running — long-lived actors must not starve task scheduling).
            resources=_build_resources(self._opts, default_cpus=0.0),
            owner=cw.address.to_wire(),
            actor_id=actor_id.hex(),
            actor_creation=True,
            max_restarts=self._opts["max_restarts"],
            max_task_retries=self._opts["max_task_retries"],
            strategy=strategy,
            placement_group=pg_id,
            pg_bundle_index=bundle_index,
            runtime_env=_effective_runtime_env(self._opts["runtime_env"]),
            max_concurrency=int(self._opts["max_concurrency"] or 1),
        )
        with tracing.submit_span(spec.name, spec.task_id) as trace_ctx:
            spec.trace_ctx = trace_ctx
            resp = cw.create_actor(
                spec,
                name=self._opts["name"] or "",
                namespace=self._opts["namespace"] or "default",
                class_name=self._cls.__name__,
                detached=self._opts["lifetime"] == "detached",
                get_if_exists=self._opts["get_if_exists"])
        if not resp.get("ok"):
            raise exc.RayTpuError(resp.get("reason", "actor registration failed"))
        transport = self._opts["tensor_transport"]
        if resp.get("existing"):
            return ActorHandle(ActorID.from_hex(resp["actor_id"]),
                               self._cls.__name__,
                               self._opts["max_task_retries"], transport)
        return ActorHandle(actor_id, self._cls.__name__,
                           self._opts["max_task_retries"], transport)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated "
            f"directly; use .remote()")


def make_remote(obj, opts: dict):
    if isinstance(obj, type):
        return ActorClass(obj, opts)
    if callable(obj):
        return RemoteFunction(obj, opts)
    raise TypeError("@ray_tpu.remote requires a function or class")
