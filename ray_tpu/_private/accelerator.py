"""TPU/accelerator autodetection for node resource specs.

Re-design of the reference's accelerator detection
(reference: python/ray/_private/accelerator.py — TPU chip count from
/dev/accel* at :155, version from GCE metadata/env at :177-212;
python/ray/util/accelerators/accelerators.py:9-11 TPU-V{2,3,4} constants;
TPU_VISIBLE_CHIPS isolation in ray_constants.py).

TPU is first-class here: detection also surfaces the pod-slice topology
(worker count, slice name) as node labels, so the scheduler can gang-place
onto ICI-connected hosts (STRICT_ICI placement groups).
"""

from __future__ import annotations

import glob
import os

TPU_RESOURCE = "TPU"

# accelerator_type constants (parity: util/accelerators/accelerators.py)
TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

# Environment overrides (TPU-VM images set these; tests set them too).
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"   # e.g. "v4-32"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_SLICE_NAME_ENV = "TPU_NAME"


def detect_tpu_chip_count() -> int:
    """Count local TPU chips (reference: accelerator.py:155 /dev/accel*)."""
    visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
    if visible is not None:
        return len([c for c in visible.split(",") if c.strip() != ""])
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def detect_tpu_version() -> str | None:
    """Map an accelerator-type string like 'v4-32' to TPU-V4 (reference:
    accelerator.py:177-212 reads GCE metadata; here env-only, metadata
    lookup is a provider concern in the autoscaler)."""
    acc_type = os.environ.get(TPU_ACCELERATOR_TYPE_ENV, "")
    if not acc_type:
        return None
    gen = acc_type.split("-")[0].lower()
    return {
        "v2": TPU_V2, "v3": TPU_V3, "v4": TPU_V4,
        "v5litepod": TPU_V5E, "v5e": TPU_V5E, "v5p": TPU_V5P, "v6e": TPU_V6E,
    }.get(gen)


def tpu_slice_labels() -> dict[str, str]:
    """Node labels describing the ICI slice this host belongs to.

    `tpu-slice`: slice identity — nodes sharing it are ICI-connected and
    live/die together (the gang-lease unit, SURVEY.md §7 hard parts).
    `tpu-worker-id`: this host's index within the slice.
    """
    labels = {}
    slice_name = os.environ.get(TPU_SLICE_NAME_ENV)
    if slice_name:
        labels["tpu-slice"] = slice_name
    # Generic provider-node identity (non-TPU clouds: the AWS provider's
    # user-data bootstrap sets it so the autoscaler can map the GCS node
    # back to the instance for idle-drain-terminate).
    node_name = os.environ.get("RAY_TPU_NODE_NAME")
    if node_name:
        labels["node-name"] = node_name
    worker_id = os.environ.get(TPU_WORKER_ID_ENV)
    if worker_id is not None:
        labels["tpu-worker-id"] = worker_id
    acc_type = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
    if acc_type:
        labels["tpu-accelerator-type"] = acc_type
    return labels


# ---------------------------------------------------------------------------
# Per-lease accelerator isolation for pool workers.
#
# Reference behavior: the raylet exports CUDA_VISIBLE_DEVICES /
# TPU_VISIBLE_CHIPS per lease so a worker that did not reserve an
# accelerator cannot touch it (ray_constants.py TPU_VISIBLE_CHIPS).
# JAX analog: the platform choice is fixed at first backend use, and on
# images that force-register a TPU platform the JAX_PLATFORMS env var is
# ignored — only jax.config.update("jax_platforms", ...) works.  So pool
# workers install an import hook that pins jax to CPU at jax-import time
# unless the task being executed holds a TPU resource lease.  Without it,
# two CPU-only workers importing jax would both open the (single-process)
# TPU and deadlock.
# ---------------------------------------------------------------------------

_current_task_has_tpu: bool = False
# Platform jax was actually pinned to in this process (None = not yet
# imported/pinned). Frozen after first jax import — jax cannot switch
# backends once initialized.
_pinned_platform: str | None = None


def set_current_task_tpu(has_tpu: bool) -> None:
    global _current_task_has_tpu
    _current_task_has_tpu = has_tpu


def pinned_platform() -> str | None:
    return _pinned_platform


def current_task_needs_fresh_worker() -> bool:
    """True when this worker's frozen jax pin can't serve the current
    task: jax is pinned to CPU but the task holds a TPU lease.  The task
    must be retried on a fresh worker (whose first import will pin TPU)."""
    return _current_task_has_tpu and _pinned_platform == "cpu"


def _pin_jax_platform(jax_module) -> None:
    global _pinned_platform
    plat = os.environ.get("RAY_TPU_JAX_PLATFORM")
    if plat is None and not _current_task_has_tpu:
        plat = "cpu"
    _pinned_platform = plat or "tpu"
    if plat:
        try:
            jax_module.config.update("jax_platforms", plat)
        except Exception:
            pass


def install_worker_jax_isolation() -> None:
    """Install the jax import hook (idempotent; pool workers only)."""
    import importlib.abc
    import importlib.machinery
    import sys

    if "jax" in sys.modules:
        # Pre-imported jax (site hooks, or a zygote-forked worker): no
        # backend is initialized yet, so the pin can — and must — wait
        # until the first task, when the TPU lease is actually known.
        # Pinning "cpu" here would freeze every such worker off the TPU.
        return
    if any(isinstance(f, _JaxIsolationFinder) for f in sys.meta_path):
        return
    sys.meta_path.insert(0, _JaxIsolationFinder())


def ensure_jax_pinned() -> None:
    """Task-time pin for workers whose jax was pre-imported (the import
    hook never fired). Safe to call repeatedly; first call wins, matching
    the freeze-on-first-import semantics of the hook path."""
    import sys

    if _pinned_platform is None and "jax" in sys.modules:
        _pin_jax_platform(sys.modules["jax"])


class _JaxIsolationFinder:
    """Meta-path finder that pins the jax platform right after the top-level
    `jax` package finishes importing (before any backend is initialized)."""

    _in_find = False

    def find_spec(self, name, path=None, target=None):
        if name != "jax" or _JaxIsolationFinder._in_find:
            return None
        import importlib.util

        _JaxIsolationFinder._in_find = True
        try:
            spec = importlib.util.find_spec("jax")
        finally:
            _JaxIsolationFinder._in_find = False
        if spec is None or spec.loader is None:
            return None
        spec.loader = _PinningLoader(spec.loader)
        return spec


class _PinningLoader:
    def __init__(self, inner):
        self._inner = inner

    def create_module(self, spec):
        return self._inner.create_module(spec)

    def exec_module(self, module):
        self._inner.exec_module(module)
        _pin_jax_platform(module)

    def __getattr__(self, item):
        return getattr(self._inner, item)


def node_resources_and_labels() -> tuple[dict, dict]:
    """Auto-detected resource/label additions for this node."""
    resources: dict[str, float] = {}
    chips = detect_tpu_chip_count()
    if chips:
        resources[TPU_RESOURCE] = float(chips)
        version = detect_tpu_version()
        if version:
            resources[f"accelerator_type:{version}"] = 1.0
    return resources, tpu_slice_labels()
