"""HBM-resident device object plane: pass jax.Arrays between tasks and
actors without a host round-trip.

Re-design target (reference: Ray GPU objects / compiled-graphs
accelerator-native transport; Pathways keeps tensors resident in device
memory and moves them over ICI/DCN): today every device array crossing a
task boundary pays device_get → pickle → shm → TCP → device_put. Here the
producing worker PINS the live jax.Array in a per-process registry keyed
by the return object id, and only a small descriptor (shape / dtype /
sharding / owner / device set) travels the plasma path as the object's
value (serialization.KIND_DEVICE). Resolution picks the cheapest route:

  same process   → hand over the live array (zero copy, identity)
  same-mesh peer → collective send/recv over the util/collective peer
                   plane (ICI/DCN framing: raw buffer + CollectiveDeliver
                   mailbox, no pickle, no object-store round trip)
  otherwise      → transparent host-path fallback (owner gathers to host,
                   consumer device_puts), counted so benchmarks and tests
                   can assert which route ran

Failure semantics: when the pinning worker dies the descriptor reports
the object lost; if the resolving process OWNS the object, the existing
lineage reconstruction in worker.py (_try_reconstruct) re-executes the
creating task, which re-pins fresh arrays. Refcount release of the
owning ObjectRef unpins the HBM bytes (worker._free_object notifies the
pinning worker).

Observability: pinned bytes/objects and per-route transfer counts export
through util/metrics gauges, util/state.list_device_objects(), the
`ray_tpu device-objects` CLI verb and the /api/device_objects dashboard
endpoint.

This module must stay importable without initializing jax (workers pin
their backend lazily per accelerator.py) — jax is only touched through
sys.modules.
"""

from __future__ import annotations

import itertools
import logging
import sys
import threading
import time

import numpy as np

from ray_tpu import exceptions as exc
from ray_tpu._private.common import require_fields

logger = logging.getLogger(__name__)

COLLECTIVE_GROUP = "__device_plane__"

_counter_lock = threading.Lock()
_counters = {
    "total_pinned": 0,       # arrays ever pinned
    "in_process": 0,         # zero-copy same-process handovers
    "collective": 0,         # peer-plane (ICI/DCN) transfers completed
    "collective_out": 0,     # peer-plane transfers served (producer side)
    "host_fallback": 0,      # host-path fallbacks completed
    "host_out": 0,           # host-path pulls served (producer side)
    "lost": 0,               # resolutions that found the pin gone
    "released": 0,           # arrays unpinned by refcount release
    "evacuated_out": 0,      # arrays shipped off a draining node
    "evacuated_in": 0,       # arrays re-pinned here by an evacuation
    "errors_total": 0,       # swallowed-but-logged failures on the
                             # pull/evacuation/repin degraded paths
}
_handoff_seq = itertools.count(1)


def _count(name: str, n: int = 1) -> None:
    with _counter_lock:
        _counters[name] = _counters.get(name, 0) + n
    _update_gauges()  # throttled: O(registry) work at most ~1/s


def counters() -> dict:
    with _counter_lock:
        return dict(_counters)


def _is_jax_array(value) -> bool:
    mod = type(value).__module__
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


def _local_platform() -> str | None:
    """Backend of THIS process's jax, or None when jax isn't imported.
    Only called on resolution paths that are about to materialize device
    arrays anyway, so triggering backend init here is free."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.default_backend()
    except Exception:
        return None


def _local_device_ids() -> list[int]:
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    try:
        return sorted(d.id for d in jax.devices())
    except Exception:
        return []


def _to_device(np_value: np.ndarray):
    """One host→HBM DMA on the consumer; plain numpy when jax is absent
    (same restore contract as the host-path pickle restore — shared so
    the two paths cannot diverge)."""
    from ray_tpu._private.serialization import _restore_jax_array

    return _restore_jax_array(np_value)


def _np_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype string. bfloat16/fp8 names are only
    registered with numpy once ml_dtypes loads — a jax-less consumer
    pulling a bf16 tensor must not crash in frombuffer."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401  (registers the extended dtypes)

        return np.dtype(name)


class DeviceObjectMeta:
    """Wire-light descriptor of one pinned array (the only thing that
    travels the object path for a device object)."""

    __slots__ = ("key", "shape", "dtype", "nbytes", "owner_addr",
                 "platform", "device_ids", "sharding")

    def __init__(self, key, shape, dtype, nbytes, owner_addr, platform,
                 device_ids, sharding):
        self.key = key
        self.shape = shape
        self.dtype = dtype
        self.nbytes = nbytes
        self.owner_addr = owner_addr  # Address.to_wire() of pin worker
        self.platform = platform
        self.device_ids = device_ids
        self.sharding = sharding

    def __reduce__(self):
        return (DeviceObjectMeta,
                (self.key, self.shape, self.dtype, self.nbytes,
                 self.owner_addr, self.platform, self.device_ids,
                 self.sharding))

    def to_dict(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}


class DeviceObjectStub:
    """Placeholder stored in place of a pinned jax.Array inside a
    KIND_DEVICE payload; get() swaps it for the resolved array."""

    __slots__ = ("meta",)

    def __init__(self, meta: DeviceObjectMeta):
        self.meta = meta

    def __reduce__(self):
        return (DeviceObjectStub, (self.meta,))

    def __repr__(self):
        return (f"DeviceObjectStub({self.meta.key}, shape="
                f"{tuple(self.meta.shape)}, dtype={self.meta.dtype}, "
                f"{self.meta.nbytes}B @ {self.meta.platform})")


class DeviceRegistry:
    """Per-process pin table: key → live jax.Array. Pinning holds the
    array's HBM for as long as the owning object is referenced (the
    plasma analogue of a sealed buffer, except the buffer IS the device
    allocation)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, tuple] = {}  # key -> (array, meta, ts)
        # prefix -> Address wire of the process that OWNS the ObjectRef
        # whose payload these pins back. The pin worker needs it exactly
        # once: a drain evacuation re-homes the arrays to the ref owner
        # (evacuate()) — without it the pins die with the node.
        self._ref_owners: dict[str, list | None] = {}

    def note_ref_owner(self, prefix: str, owner_wire) -> None:
        with self._lock:
            self._ref_owners[prefix] = owner_wire

    def ref_owner(self, prefix: str):
        with self._lock:
            return self._ref_owners.get(prefix)

    def pin(self, key: str, array, cw=None) -> DeviceObjectMeta:
        try:
            devices = list(array.devices())
            device_ids = sorted(d.id for d in devices)
            platform = devices[0].platform if devices else "cpu"
        except Exception:
            device_ids, platform = [], "cpu"
        meta = DeviceObjectMeta(
            key=key,
            shape=[int(s) for s in array.shape],
            dtype=str(array.dtype),
            nbytes=int(getattr(array, "nbytes", 0)),
            owner_addr=(cw.address.to_wire()
                        if cw is not None and cw.address else None),
            platform=platform,
            device_ids=device_ids,
            sharding=str(getattr(array, "sharding", "")))
        with self._lock:
            self._entries[key] = (array, meta, time.time())
        _count("total_pinned")
        return meta

    def get(self, key: str):
        with self._lock:
            entry = self._entries.get(key)
        return entry[0] if entry is not None else None

    def get_entry(self, key: str):
        with self._lock:
            return self._entries.get(key)

    def release(self, key: str) -> bool:
        with self._lock:
            gone = self._entries.pop(key, None)
        if gone is not None:
            _count("released")
        return gone is not None

    def release_prefix(self, prefix: str, *, counted: bool = True) -> int:
        """Unpin every leaf of one device object (keys are
        '<prefix>#<leaf-index>'). counted=False for internal unpins
        (drain evacuation moves arrays, it does not release them — the
        'released' gauge must stay a pure refcount-release count)."""
        with self._lock:
            keys = [k for k in self._entries
                    if k == prefix or k.startswith(prefix + "#")]
            for k in keys:
                del self._entries[k]
            self._ref_owners.pop(prefix, None)
        if keys and counted:
            _count("released", len(keys))
        return len(keys)

    def stats(self) -> dict:
        with self._lock:
            n = len(self._entries)
            total = sum(e[1].nbytes for e in self._entries.values())
        return {"pinned_objects": n, "pinned_bytes": total,
                "counters": counters()}

    def entries(self) -> list[dict]:
        with self._lock:
            snap = [(k, e[1], e[2]) for k, e in self._entries.items()]
        return [{"key": k, "shape": m.shape, "dtype": m.dtype,
                 "nbytes": m.nbytes, "platform": m.platform,
                 "device_ids": m.device_ids, "pinned_ts": ts}
                for k, m, ts in snap]


_registry: DeviceRegistry | None = None
_registry_lock = threading.Lock()


def registry() -> DeviceRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = DeviceRegistry()
        return _registry


# ---------- metrics ----------

_gauges = None
_gauge_ts = [float("-inf")]
_GAUGE_MIN_INTERVAL_S = 1.0


def _update_gauges(force: bool = False) -> None:
    """Keep the util/metrics gauges current (pinned-HBM bytes/objects,
    per-route transfer counts). Throttled: pin/resolve hot paths tick
    counters per leaf, and rebuilding five gauges plus an O(registry)
    byte sum per tick would make extraction O(N^2) — at most one rebuild
    per second unless a scrape forces it. Never allowed to break the
    data path."""
    global _gauges
    now = time.monotonic()
    if not force and now - _gauge_ts[0] < _GAUGE_MIN_INTERVAL_S:
        return
    _gauge_ts[0] = now
    try:
        from ray_tpu.util.metrics import Gauge

        if _gauges is None:
            _gauges = {
                "bytes": Gauge("ray_tpu_device_objects_pinned_bytes",
                               "bytes pinned in HBM by the device object "
                               "plane"),
                "count": Gauge("ray_tpu_device_objects_pinned",
                               "arrays pinned by the device object plane"),
                "transfers": Gauge("ray_tpu_device_object_transfers",
                                   "device-object resolutions by route",
                                   ("route",)),
                "lost": Gauge("ray_tpu_device_objects_lost",
                              "device objects found lost at resolution"),
                "released": Gauge("ray_tpu_device_objects_released",
                                  "arrays unpinned by refcount release"),
                "evacuated": Gauge("ray_tpu_device_objects_evacuated",
                                   "arrays moved by drain evacuation",
                                   ("direction",)),
            }
        reg = registry()
        with reg._lock:
            n = len(reg._entries)
            total = sum(e[1].nbytes for e in reg._entries.values())
        with _counter_lock:
            snap = dict(_counters)
        g = _gauges
        g["bytes"].set(total)
        g["count"].set(n)
        for route in ("in_process", "collective", "host_fallback"):
            g["transfers"].set(snap.get(route, 0), tags={"route": route})
        g["lost"].set(snap.get("lost", 0))
        g["released"].set(snap.get("released", 0))
        for direction in ("out", "in"):
            g["evacuated"].set(snap.get(f"evacuated_{direction}", 0),
                               tags={"direction": direction})
    except Exception:
        pass


def export_device_object_gauges() -> dict:
    """Refresh the device-plane gauges and return the local stats snap
    (scrape-path hook, like metrics.export_pump_stats)."""
    _update_gauges(force=True)
    return registry().stats()


# ---------- extract / resolve ----------

def tree_map(value, fn, is_leaf):
    """Minimal pytree map over dict/list/tuple/namedtuple containers:
    the ONE traversal shared by extraction, resolution, and consumers
    (a fifth hand-rolled walker is how container-type fixes diverge)."""

    def walk(v):
        if is_leaf(v):
            return fn(v)
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, tuple):
            walked = tuple(walk(x) for x in v)
            if type(v) is not tuple and hasattr(v, "_fields"):
                return type(v)(*walked)  # namedtuple
            return walked
        if isinstance(v, list):
            return [walk(x) for x in v]
        return v

    return walk(value)


def extract_arrays(value, prefix: str, cw=None):
    """Pin every jax.Array leaf of `value` under '<prefix>#<i>' and
    replace it with a DeviceObjectStub. Returns (stubbed_value,
    total_bytes, n_leaves); n_leaves == 0 means `value` is returned
    untouched and should take the normal host path."""
    reg = registry()
    state = {"n": 0, "bytes": 0}

    def pin(v):
        key = f"{prefix}#{state['n']}"
        meta = reg.pin(key, v, cw)
        state["n"] += 1
        state["bytes"] += meta.nbytes
        return DeviceObjectStub(meta)

    out = tree_map(value, pin, _is_jax_array)
    if state["n"] == 0:
        return value, 0, 0
    return out, state["bytes"], state["n"]


def choose_route(meta: DeviceObjectMeta) -> str:
    """Transfer-route decision for a non-local stub (the same-process
    case never reaches here — the registry hit wins first):

      collective — producer and consumer share a mesh: same non-cpu
                   platform and overlapping device ids (ICI), or the
                   RAY_TPU_DEVICE_COLLECTIVE=1 override (DCN peers that
                   opted into the peer plane).
      host       — everything else: transparent host-path fallback.
    """
    import os

    if os.environ.get("RAY_TPU_DEVICE_COLLECTIVE") == "1":
        return "collective"
    plat = _local_platform()
    if plat and plat != "cpu" and plat == meta.platform:
        if set(meta.device_ids) & set(_local_device_ids()):
            return "collective"
    return "host"


def _is_stub(v) -> bool:
    return isinstance(v, DeviceObjectStub)


def retarget_stubs(value, owner_addr):
    """Point every stub at a fresh pinning worker. After lineage
    reconstruction the re-executed task pins under the SAME keys (the
    prefix embeds the task id), but a store-resident stub payload is not
    rewritten (_write_to_store skips existing objects) — the owner's
    refreshed dev_info carries the live address; the descriptor bytes
    may still carry the dead one."""

    def fix(stub):
        m = stub.meta
        return DeviceObjectStub(DeviceObjectMeta(
            m.key, m.shape, m.dtype, m.nbytes, owner_addr, m.platform,
            m.device_ids, m.sharding))

    return tree_map(value, fix, _is_stub)


def resolve_value(value, cw):
    """Swap every DeviceObjectStub in a deserialized KIND_DEVICE payload
    for the live array, via the cheapest route. Remote leaves are
    grouped by pinning worker and fetched with ONE batched pull per
    worker — an N-leaf param tree costs one round trip, not N. Raises
    DeviceObjectLostError when a pin is gone (owner handles lineage
    reconstruction; borrowers surface the loss)."""
    reg = registry()
    resolved: dict[str, object] = {}
    remote: dict[tuple, list[DeviceObjectMeta]] = {}

    def scan(stub):
        meta = stub.meta
        if meta.key not in resolved:
            local = reg.get(meta.key)
            if local is not None:
                _count("in_process")
                resolved[meta.key] = local
            else:
                addr_key = tuple(meta.owner_addr) if meta.owner_addr \
                    else None
                group = remote.setdefault(addr_key, [])
                if all(m.key != meta.key for m in group):
                    group.append(meta)
        return stub

    tree_map(value, scan, _is_stub)
    for metas in remote.values():
        resolved.update(_pull_batch(metas, cw))
    return tree_map(value, lambda s: resolved[s.meta.key], _is_stub)


def _pull_batch(metas: list[DeviceObjectMeta], cw) -> dict:
    """Fetch all pinned arrays of ONE pinning worker in a single RPC;
    returns {key: array}."""
    from ray_tpu._private import rpc
    from ray_tpu._private.common import Address

    first = metas[0]
    if cw is None or first.owner_addr is None:
        raise exc.DeviceObjectLostError(
            first.key, f"device object {first.key} has no reachable pin "
                       "owner (produced by a process with no runtime?)")
    addr = Address.from_wire(first.owner_addr)
    if addr.worker_id == cw.worker_id:
        # We ARE the pinning process but the registry missed: the pin was
        # released (or this is a restarted incarnation) — the data is gone.
        raise exc.DeviceObjectLostError(
            first.key, f"device object {first.key} is no longer pinned "
                       "in this process")
    route = choose_route(first)
    plane = None
    if route == "collective":
        # The peer plane's CollectiveDeliver mailbox must exist BEFORE
        # the producer's sends can arrive.
        try:
            from ray_tpu.util.collective.collective import _get_peer_plane

            plane = _get_peer_plane()
        except Exception:
            route = "host"
    keys = [m.key for m in metas]

    async def call():
        conn = await cw._owner_conn(addr)
        return await conn.call(
            "DeviceObjectPull",
            {"keys": keys, "route": route,
             "requester": cw.worker_id,
             "requester_addr": cw.address.to_wire()},
            timeout=cw.config.rpc_call_timeout_s)

    try:
        resp = cw._run(call())
    except (rpc.RpcError, OSError, ConnectionError, TimeoutError) as e:
        raise exc.DeviceObjectLostError(
            first.key, f"pin owner of device objects {keys[:3]} "
                       f"unreachable: {e}") from None
    missing = resp.get("missing") or []
    if missing:
        raise exc.DeviceObjectLostError(
            missing[0], f"device object {missing[0]} is no longer pinned "
                        f"on worker {addr.worker_id[:12]}")
    out = {}
    if resp.get("status") == "collective":
        for tag in resp["tags"]:
            try:
                np_value = plane.recv(COLLECTIVE_GROUP, tag, timeout=60.0)
            except TimeoutError as e:
                # The producer's notify only confirms a socket write; a
                # dropped connection after the reply loses the payload.
                # This IS an object loss — surface it through the
                # lineage-recovery contract, not a bare TimeoutError.
                raise exc.DeviceObjectLostError(
                    tag, f"collective transfer of device object {tag} "
                         f"never arrived: {e}") from None
            _count("collective")
            out[tag] = _to_device(np_value)
        return out
    if plane is not None:
        # A collective attempt that degraded mid-batch already delivered
        # some payloads into our mailbox: drop them (the host reply is
        # authoritative) or they strand for the process lifetime.
        for tag in resp.get("stray_tags") or []:
            plane.discard(COLLECTIVE_GROUP, tag)
    # host fallback: the reply carries the gathered bytes per key.
    for item in resp["items"]:
        np_value = np.frombuffer(
            bytearray(item["data"]),
            dtype=_np_dtype(item["dtype"])).reshape(item["shape"])
        _count("host_fallback")
        out[item["key"]] = _to_device(np_value)
    return out


# ---------- producer-side RPC handlers (worker.py delegates here) ----------

async def handle_pull(cw, payload: dict) -> dict:
    """Serve a batch of pinned arrays to one consumer. Collective route:
    push each raw buffer through the requester's util/collective
    peer-plane mailbox (direct worker→worker framing, no pickle, no
    object store — the DCN/ICI plane); host route: return the gathered
    bytes inline. Every host gather + copy runs in an executor — a
    multi-hundred-MB KV pull must not stall this worker's whole RPC
    loop (heartbeats, TaskDone) behind an HBM→host DMA."""
    import asyncio

    from ray_tpu._private.common import Address

    keys = payload.get("keys")
    if not keys:
        # Single-object form: the batch field is absent, 'key' is the
        # frame's one required field.
        require_fields(payload, "key", method="handle_pull")
        keys = [payload["key"]]
    reg = registry()
    entries, missing = [], []
    for key in keys:
        entry = reg.get_entry(key)
        if entry is None:
            missing.append(key)
        else:
            entries.append((key, entry[0]))
    if missing:
        return {"status": "gone", "missing": missing}
    loop = asyncio.get_running_loop()

    def gather(array):
        np_value = np.asarray(array)  # the (single) host gather
        return (str(np_value.dtype), list(np_value.shape),
                np_value.tobytes())

    gathered = [(key, await loop.run_in_executor(None, gather, array))
                for key, array in entries]
    delivered: list[str] = []
    if payload.get("route") == "collective" and payload.get("requester_addr"):
        require_fields(payload, "requester_addr", method="handle_pull")
        try:
            conn = await cw._owner_conn(
                Address.from_wire(payload["requester_addr"]))
            for key, (dtype, shape, data) in gathered:
                await conn.notify("CollectiveDeliver", {
                    "group": COLLECTIVE_GROUP, "tag": key,
                    "dtype": dtype, "shape": shape, "data": data})
                delivered.append(key)
            _count("collective_out", len(delivered))
            return {"status": "collective", "tags": delivered}
        except Exception:
            # Fall through to the host reply; tags already delivered
            # are reported so the consumer drains its mailbox (raw
            # tensor buffers must not strand in _PeerPlane._inbox).
            _count("errors_total")
            logger.warning(
                "handle_pull: collective push to %s failed after %d/%d "
                "tags; serving host route", payload["requester_addr"],
                len(delivered), len(gathered), exc_info=True)
    _count("host_out", len(gathered))
    return {"status": "host", "stray_tags": delivered,
            "items": [{"key": key, "dtype": dtype, "shape": shape,
                       "data": data}
                      for key, (dtype, shape, data) in gathered]}


async def handle_release(cw, payload: dict) -> dict:
    require_fields(payload, "prefix", method="handle_release")
    n = registry().release_prefix(payload["prefix"])
    return {"released": n}


async def handle_stats(cw, payload: dict) -> dict:
    _update_gauges(force=True)  # stats fan-out doubles as gauge refresh
    out = registry().stats()
    out["worker_id"] = cw.worker_id
    if payload.get("entries"):
        out["entries"] = registry().entries()
    return out


# ---------- drain-path evacuation ----------

# Callbacks that materialize last-moment pins (e.g. a serving engine
# snapshotting in-flight stream KV) — run to completion INSIDE
# evacuate() before the registry is snapshotted. A DrainNotice listener
# cannot do this: the raylet fires DeviceObjectEvacuate milliseconds
# after the notice, and pins created on a listener thread lose that
# race and silently miss the evacuation.
_evac_preparers: list = []


def add_evacuation_preparer(fn) -> None:
    """Register fn() to run (in an executor thread, awaited) before a
    drain evacuation gathers this process's pins."""
    if fn not in _evac_preparers:
        _evac_preparers.append(fn)


def remove_evacuation_preparer(fn) -> None:
    try:
        _evac_preparers.remove(fn)
    except ValueError:
        pass


async def evacuate(cw) -> dict:
    """Re-home every pinned array whose ObjectRef owner lives off this
    node — called by the raylet's drain pipeline before the node dies.
    Leaves are grouped per device object (prefix) and shipped to the
    ref-owner process, which re-pins them under the SAME keys and
    refreshes its descriptor (DeviceObjectRepin). Route: the peer-plane
    collective mailbox when RAY_TPU_DEVICE_COLLECTIVE=1 (raw buffers,
    no pickle), else the counted host fallback (gather + inline bytes).
    Pins whose ref owner dies with this node are skipped — there is no
    surviving reference to preserve them for."""
    import asyncio
    import os

    from ray_tpu._private.common import Address

    loop = asyncio.get_running_loop()
    for fn in list(_evac_preparers):
        try:
            await loop.run_in_executor(None, fn)
        except Exception:
            logger.warning("evacuation preparer failed; continuing with "
                           "existing pins", exc_info=True)
    reg = registry()
    with reg._lock:
        snap = list(reg._entries.items())
        owners = dict(reg._ref_owners)
    by_prefix: dict[str, list] = {}
    for key, entry in snap:
        by_prefix.setdefault(key.split("#", 1)[0], []).append((key, entry))
    stats = {"evacuated_objects": 0, "evacuated_bytes": 0, "skipped": 0,
             "routes": {}}
    want_collective = os.environ.get("RAY_TPU_DEVICE_COLLECTIVE") == "1"
    for prefix, leaves in by_prefix.items():
        owner_wire = owners.get(prefix)
        if not owner_wire:
            stats["skipped"] += len(leaves)
            continue
        addr = Address.from_wire(owner_wire)
        if addr.worker_id == cw.worker_id or addr.node_id == cw.node_id:
            # The owner's process dies with this node: its refs (and any
            # consumer's recovery path) die too — nothing to preserve.
            stats["skipped"] += len(leaves)
            continue

        def gather_all(leaves=leaves):
            out = []
            for key, (array, meta, _ts) in leaves:
                np_value = np.asarray(array)
                out.append((key, str(np_value.dtype),
                            list(np_value.shape), np_value.tobytes(),
                            meta.nbytes))
            return out

        try:
            gathered = await loop.run_in_executor(None, gather_all)
            conn = await cw._owner_conn(addr)
            resp = None
            route = "host"
            delivered_tags: list = []
            if want_collective:
                # Three steps, because the receiver's mailbox must exist
                # BEFORE any raw-buffer send (an unknown-handler notify
                # is silently dropped): prepare (owner arms its
                # _PeerPlane, or refuses and we go host with no stall) →
                # deliver the buffers → commit (owner recvs + pins, and
                # discards every tag on failure so nothing strands).
                # Exceptions anywhere degrade to the host route too —
                # the host Repin then carries the delivered tags as
                # stale so the owner sweeps its mailbox.
                route = "collective"
                tags = [key for key, *_ in gathered]
                try:
                    resp = await conn.call(
                        "DeviceObjectRepin",
                        {"prefix": prefix, "route": "collective",
                         "phase": "prepare", "tags": tags}, timeout=15)
                    if resp.get("ok"):
                        for key, dtype, shape, data, _nb in gathered:
                            await conn.notify("CollectiveDeliver", {
                                "group": COLLECTIVE_GROUP, "tag": key,
                                "dtype": dtype, "shape": shape,
                                "data": data})
                            delivered_tags.append(key)
                        resp = await conn.call(
                            "DeviceObjectRepin",
                            {"prefix": prefix, "route": "collective",
                             "phase": "commit", "tags": tags},
                            timeout=60)
                except Exception:
                    resp = {}
                if not resp.get("ok"):
                    resp = None
                    route = "host"
            if resp is None:
                resp = await conn.call("DeviceObjectRepin", {
                    "prefix": prefix, "route": "host",
                    "stale_tags": delivered_tags,
                    "items": [{"key": key, "dtype": dtype,
                               "shape": shape, "data": data}
                              for key, dtype, shape, data, _nb
                              in gathered]}, timeout=60)
            if not resp.get("ok"):
                stats["skipped"] += len(leaves)
                continue
        except Exception:
            stats["skipped"] += len(leaves)
            continue
        reg.release_prefix(prefix, counted=False)
        nbytes = sum(nb for *_rest, nb in gathered)
        stats["evacuated_objects"] += len(leaves)
        stats["evacuated_bytes"] += nbytes
        stats["routes"][route] = stats["routes"].get(route, 0) + len(leaves)
        _count("evacuated_out", len(leaves))
    return stats


async def handle_repin(cw, payload: dict) -> dict:
    """Ref-owner side of a drain evacuation: accept the arrays a dying
    node shipped over, pin them in THIS process under their original
    keys, and repoint the owned object's descriptor here — consumers
    (and our own gets) then resolve against a live pin instead of
    falling into lineage reconstruction."""
    import asyncio

    require_fields(payload, "prefix", method="handle_repin")
    prefix = payload["prefix"]
    arrays: dict[str, np.ndarray] = {}
    if payload.get("route") == "collective":
        try:
            from ray_tpu.util.collective.collective import _get_peer_plane

            plane = _get_peer_plane()
        except Exception as e:
            return {"ok": False, "error": f"no peer plane: {e}"}
        if payload.get("phase") == "prepare":
            # Mailbox armed; the sender may deliver now. Nothing was
            # sent yet, so a refusal above costs the drain nothing.
            return {"ok": True}
        loop = asyncio.get_running_loop()
        require_fields(payload, "tags", method="handle_repin")
        try:
            for tag in payload["tags"]:
                arrays[tag] = await loop.run_in_executor(
                    None, lambda t=tag: plane.recv(COLLECTIVE_GROUP, t,
                                                   timeout=10.0))
        except Exception as e:
            # Partial failure: raw tensor buffers already delivered for
            # the remaining tags must not strand in the mailbox for the
            # process lifetime (the sender retries via the host route).
            for tag in payload["tags"]:
                if tag not in arrays:
                    try:
                        plane.discard(COLLECTIVE_GROUP, tag)
                    except Exception:
                        _count("errors_total")
                        logger.warning(
                            "handle_repin: mailbox discard of %r failed "
                            "— buffer may strand until process exit",
                            tag, exc_info=True)
            return {"ok": False, "error": f"collective recv failed: {e}"}
    else:
        # Host route after a degraded collective attempt: buffers the
        # sender already delivered into our mailbox are stale (the host
        # payload is authoritative) — sweep them, but only from an
        # ALREADY-EXISTING plane (no plane = the notifies were dropped
        # at dispatch; arming one just to sweep would be waste).
        if payload.get("stale_tags"):
            from ray_tpu.util.collective import collective as _coll

            require_fields(payload, "stale_tags", method="handle_repin")
            plane = _coll._peer_plane
            if plane is not None:
                for tag in payload["stale_tags"]:
                    try:
                        plane.discard(COLLECTIVE_GROUP, tag)
                    except Exception:
                        _count("errors_total")
                        logger.warning(
                            "handle_repin: stale-tag discard of %r "
                            "failed", tag, exc_info=True)
        require_fields(payload, "items", method="handle_repin")
        for item in payload["items"]:
            arrays[item["key"]] = np.frombuffer(
                bytearray(item["data"]),
                dtype=_np_dtype(item["dtype"])).reshape(item["shape"])
    reg = registry()
    n = total = 0
    for key, np_value in arrays.items():
        meta = reg.pin(key, _to_device(np_value), cw)
        total += meta.nbytes
        n += 1
    own_wire = cw.address.to_wire() if cw.address else None
    reg.note_ref_owner(prefix, own_wire)
    _count("evacuated_in", n)
    cw._post(cw._repoint_device_pin, prefix, own_wire)
    return {"ok": True, "repinned": n, "bytes": total}


def note_lost() -> None:
    _count("lost")


# ---------- driver/actor-facing helpers ----------

def device_put(value):
    """Pin a (tree of) jax.Array(s) in THIS process's registry and store
    only the descriptor as the object value — the device-plane analogue
    of ray_tpu.put. Consumers resolve via the cheapest route; freeing the
    returned ref unpins. Values with no jax.Array leaves fall back to a
    plain put."""
    import ray_tpu
    from ray_tpu._private import serialization
    from ray_tpu._private.api_internal import (DeviceObjectRef,
                                               collect_nested_refs,
                                               get_core_worker)
    from ray_tpu._private.ids import ObjectID

    cw = get_core_worker()
    oid = ObjectID.for_put(cw._current_task_id, next(cw._put_counter))
    prefix = f"put:{oid.hex()[:16]}:{next(_handoff_seq)}"
    stubbed, total, n = extract_arrays(value, prefix, cw)
    if n == 0:
        return ray_tpu.put(value)
    # Self-owned pin: evacuation has nothing to move (the ref dies with
    # this process), but the owner record keeps the table uniform.
    registry().note_ref_owner(prefix, cw.address.to_wire())
    # Refs embedded beside the arrays live as long as the put container
    # (the same container tracking put() applies).
    with collect_nested_refs() as sink:
        sobj = serialization.serialize(stubbed,
                                       kind=serialization.KIND_DEVICE)
    if sink:
        cw._post(cw._track_container, oid.hex(), list(sink))
    cw._run(cw._store_owned(oid, sobj))
    dev_info = [cw.address.to_wire(), prefix, total, n]
    cw._post(cw._set_device_info, oid.hex(), dev_info)
    return DeviceObjectRef(oid, cw.address)


def local_handoff(tag: str, value):
    """Same-process producer→consumer handoff through the plane (the
    serve prefill→decode KV route): pin, resolve (registry hit — zero
    copy), unpin. Ticks the in_process counters and the pinned-HBM gauge
    so the handoff is observable; returns the SAME live arrays."""
    prefix = f"{tag}:{next(_handoff_seq)}"
    stubbed, _total, n = extract_arrays(value, prefix, None)
    if n == 0:
        return value
    try:
        return resolve_value(stubbed, None)
    finally:
        registry().release_prefix(prefix)
