"""ctypes bindings for the native object-transfer plane (src/transfer.cc).

The server half runs inside the raylet process (one thread per peer
connection, payload bytes served straight out of the shm arena); the
fetch half pulls a peer's object directly into the local arena. Python
only initiates transfers — no object byte ever crosses the interpreter
(reference: src/ray/object_manager/ push/pull managers are likewise
native, with gRPC streaming instead of this fixed framing).
"""

from __future__ import annotations

import ctypes
import logging

logger = logging.getLogger(__name__)

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ray_tpu._private.native_build import ensure_built

    path = ensure_built(("transfer.cc", "object_store.cc"),
                        "libtputransfer.so", ("-lpthread",))
    lib = ctypes.CDLL(path)
    lib.transfer_server_start.restype = ctypes.c_void_p
    lib.transfer_server_start.argtypes = [ctypes.c_char_p,
                                          ctypes.POINTER(ctypes.c_int)]
    lib.transfer_server_stop.restype = None
    lib.transfer_server_stop.argtypes = [ctypes.c_void_p]
    lib.transfer_fetch.restype = ctypes.c_int
    lib.transfer_fetch.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int, ctypes.c_char_p]
    lib.transfer_fetch_multi.restype = ctypes.c_int
    lib.transfer_fetch_multi.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_char_p]
    _lib = lib
    return lib


class TransferServer:
    """Serves this node's store to peers. port == 0 when unavailable."""

    def __init__(self, store_path: str):
        self._handle = None
        self.port = 0
        try:
            lib = _load()
            out_port = ctypes.c_int(0)
            handle = lib.transfer_server_start(store_path.encode(),
                                               ctypes.byref(out_port))
            if handle:
                self._handle = handle
                self.port = out_port.value
        except Exception:
            logger.exception("native transfer server unavailable; "
                             "falling back to RPC object transfer")

    def stop(self):
        if self._handle is not None:
            try:
                _load().transfer_server_stop(self._handle)
            except Exception:
                pass
            self._handle = None
            self.port = 0


def fetch(store_path: str, host: str, port: int, oid_bytes: bytes) -> int:
    """Blocking native pull (run it in an executor). Returns 0 on success,
    <0 on failure (see transfer.cc)."""
    lib = _load()
    return lib.transfer_fetch(store_path.encode(), host.encode(), port,
                              oid_bytes)


def fetch_multi(store_path: str, peers: list, oid_bytes: bytes) -> int:
    """Blocking native pull striping chunks across several peers
    ([(host, port), ...]); large objects fan out over parallel
    connections (transfer.cc stripe workers + pull admission)."""
    lib = _load()
    csv = ",".join(f"{h}:{p}" for h, p in peers)
    return lib.transfer_fetch_multi(store_path.encode(), csv.encode(),
                                    oid_bytes)
