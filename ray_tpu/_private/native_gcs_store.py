"""ctypes binding for the native GCS table storage.

The store is C++ (src/gcs_store.cc, built to
ray_tpu/_private/_lib/libtpugstore.so) — the TPU-native equivalent of
the reference's gcs_table_storage over store_client (reference:
src/ray/gcs/gcs_server/gcs_table_storage.cc, store_client/
redis_store_client.h — redis is what gives the reference per-mutation
durability for GCS fault tolerance).

Rows are opaque bytes keyed (namespace, key): every put/del appends one
WAL record, fflush'd per append, so an acknowledged mutation survives a
GCS PROCESS crash (kill -9) — the GCS writes rows through HERE before
replying to mutating RPCs. OS-crash/power-loss durability additionally
needs `sync()` (fdatasync), which the GCS batches on a short debounce —
the same exposure window as the reference's default redis
appendfsync-everysec. Truncated tails and corrupt length fields stop
restart replay at the last complete record. `compact()` rewrites the
snapshot and truncates the WAL; the GCS calls it when the WAL outgrows
the snapshot.
"""

from __future__ import annotations

import ctypes

from ray_tpu._private.native_build import ensure_built

_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built("gcs_store.cc", "libtpugstore.so"))
        lib.gstore_create.restype = ctypes.c_void_p
        lib.gstore_create.argtypes = [ctypes.c_char_p]
        lib.gstore_destroy.argtypes = [ctypes.c_void_p]
        lib.gstore_put.restype = ctypes.c_int
        lib.gstore_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.gstore_del.restype = ctypes.c_int
        lib.gstore_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p]
        lib.gstore_get.restype = ctypes.c_int
        lib.gstore_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.gstore_num_rows.restype = ctypes.c_int
        lib.gstore_num_rows.argtypes = [ctypes.c_void_p]
        lib.gstore_wal_bytes.restype = ctypes.c_uint64
        lib.gstore_wal_bytes.argtypes = [ctypes.c_void_p]
        lib.gstore_scan.restype = ctypes.c_int
        lib.gstore_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_int),
                                    ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_char_p, ctypes.c_int]
        lib.gstore_namespaces.restype = ctypes.c_int
        lib.gstore_namespaces.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int]
        lib.gstore_compact.restype = ctypes.c_int
        lib.gstore_compact.argtypes = [ctypes.c_void_p]
        lib.gstore_sync.restype = ctypes.c_int
        lib.gstore_sync.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class GcsTableStore:
    """Durable (namespace, key) -> bytes tables with WAL persistence."""

    def __init__(self, path_prefix: str):
        self._lib = _get_lib()
        self._h = ctypes.c_void_p(
            self._lib.gstore_create(path_prefix.encode()))

    def close(self):
        if self._h:
            self._lib.gstore_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def put(self, ns: str, key: str, value: bytes) -> bool:
        """False when the WAL append failed (disk full, ...) — the
        caller must NOT mark the row as flushed."""
        return self._lib.gstore_put(self._h, ns.encode(), key.encode(),
                                    value, len(value)) == 0

    def delete(self, ns: str, key: str) -> bool:
        return self._lib.gstore_del(self._h, ns.encode(),
                                    key.encode()) == 0

    def get(self, ns: str, key: str) -> bytes | None:
        n = self._lib.gstore_get(self._h, ns.encode(), key.encode(),
                                 None, 0)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(max(n, 1))
        self._lib.gstore_get(self._h, ns.encode(), key.encode(), buf, n)
        return buf.raw[:n]

    def scan(self, ns: str):
        """Yield (key, value) over one namespace."""
        cursor = ctypes.c_int(0)
        ksize, vsize = 4096, 1 << 20
        kbuf = ctypes.create_string_buffer(ksize)
        vbuf = ctypes.create_string_buffer(vsize)
        while True:
            rc = self._lib.gstore_scan(self._h, ns.encode(),
                                       ctypes.byref(cursor), kbuf, ksize,
                                       vbuf, vsize)
            if rc == -2:
                # -2 means EITHER buffer was too small; grow both (a
                # huge internal_kv key can outgrow kbuf, not just vbuf).
                ksize *= 4
                vsize *= 4
                kbuf = ctypes.create_string_buffer(ksize)
                vbuf = ctypes.create_string_buffer(vsize)
                continue
            if rc < 0:
                return
            yield kbuf.value.decode(), vbuf.raw[:rc]

    def namespaces(self) -> list[str]:
        buf = ctypes.create_string_buffer(16384)
        rc = self._lib.gstore_namespaces(self._h, buf, len(buf))
        if rc <= 0:
            return []
        return buf.value.decode().split("\x1e")

    def num_rows(self) -> int:
        return self._lib.gstore_num_rows(self._h)

    def wal_bytes(self) -> int:
        return self._lib.gstore_wal_bytes(self._h)

    def compact(self) -> bool:
        return self._lib.gstore_compact(self._h) == 0

    def sync(self) -> bool:
        """fdatasync the WAL (OS-crash durability; see module doc)."""
        return self._lib.gstore_sync(self._h) == 0
