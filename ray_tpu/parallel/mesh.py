"""Device-mesh construction for SPMD parallelism.

The reference has no native notion of a device mesh — its parallelism is
orchestration of torch engines (SURVEY.md §2.4). Here the mesh is the
foundation: every training/inference program runs under one
`jax.sharding.Mesh` whose named axes carry the parallelism taxonomy:

  dp    data parallel (replicated params, sharded batch)
  fsdp  fully-sharded data parallel (ZeRO: params/opt-state sharded too)
  tp    tensor parallel (Megatron-style intra-layer sharding)
  pp    pipeline parallel (stage axis, ppermute microbatch schedule)
  sp    sequence/context parallel (ring attention / Ulysses)
  ep    expert parallel (MoE expert sharding + ragged all-to-all)

Axis sizes multiply to the device count. On TPU pods the mesh should be
built with ICI-contiguous axis ordering (innermost axes get the
fastest-wraparound ICI dimension); `jax.experimental.mesh_utils` handles
the physical layout.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclass
class MeshConfig:
    """Logical mesh shape. -1 for at most one axis: absorb remaining devices."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolved(self, num_devices: int) -> dict[str, int]:
        sizes = {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                 "sp": self.sp, "ep": self.ep, "tp": self.tp}
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = int(np.prod([v for v in sizes.values() if v != -1]))
        if unknown:
            if num_devices % known != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {known}")
            sizes[unknown[0]] = num_devices // known
        total = int(np.prod(list(sizes.values())))
        if total != num_devices:
            raise ValueError(
                f"mesh {sizes} needs {total} devices, have {num_devices}")
        return sizes


def make_mesh(config: MeshConfig | dict | None = None,
              devices=None) -> Mesh:
    """Build a Mesh with the standard axis names over the given devices."""
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig(dp=len(devices))
    if isinstance(config, dict):
        config = MeshConfig(**config)
    sizes = config.resolved(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices))
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def data_axes() -> tuple[str, ...]:
    """Axes the global batch is sharded over."""
    return ("dp", "fsdp")


def mesh_axis_size(mesh: Mesh, axes: tuple[str, ...]) -> int:
    return int(np.prod([mesh.shape[a] for a in axes]))


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> None:
    """Multi-host rendezvous: `jax.distributed.initialize` (replaces the
    reference's torch.distributed/NCCL bootstrap in Train,
    reference: python/ray/train/torch/config.py:63)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
