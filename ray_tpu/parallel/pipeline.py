"""Pipeline parallelism: shard_map microbatch pipeline over the `pp` axis.

The reference has no native PP (SURVEY.md §2.4 — DeepSpeed/Alpa only).
TPU-native design: the layer stack is sharded over the `pp` mesh axis
(stage i holds layers [i·L/p, (i+1)·L/p)); microbatches stream through
stages with `lax.ppermute` moving activations to the next stage each step.
This is the GPipe schedule expressed as a compiled collective program —
XLA overlaps the ppermute with the next microbatch's compute on ICI.

Use inside shard_map: params' leading axis is the stage axis (size p per
device after sharding), inputs are microbatched on the leading axis.
"""

from __future__ import annotations

from typing import Callable

import jax

from ray_tpu.util.collective.ops import axis_size as _axis_size
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches,
                   axis: str = "pp"):
    """Run a GPipe-style pipeline inside shard_map.

    stage_fn(params, x) -> y : one stage's computation (same shape in/out).
    stage_params: this device's stage parameters (layers of my stage).
    x_microbatches: (num_micro, mb, ...) — every device receives the full
      microbatched input; stage 0 feeds real inputs, later stages consume
      what arrives over the ring. Output: (num_micro, mb, ...) valid on the
      LAST stage (others hold garbage; caller selects).

    Total steps = num_micro + num_stages - 1 (fill + drain).
    """
    n_stages = _axis_size(axis)
    stage = lax.axis_index(axis)
    num_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    total_steps = num_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def body(step, carry):
        state, outputs = carry
        # Stage 0 ingests microbatch `step` (if within range); other stages
        # use the activation that just arrived from the previous stage.
        mb_idx = jnp.clip(step, 0, num_micro - 1)
        fresh = lax.dynamic_index_in_dim(x_microbatches, mb_idx, axis=0,
                                         keepdims=False)
        x_in = jnp.where(stage == 0, fresh, state)
        y = stage_fn(stage_params, x_in)
        # Last stage writes its result for microbatch (step - n_stages + 1).
        out_idx = jnp.clip(step - (n_stages - 1), 0, num_micro - 1)
        write = jnp.logical_and(stage == n_stages - 1,
                                step >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, axis=0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, cur), out_idx, axis=0)
        # Rotate activations to the next stage.
        state = lax.ppermute(y, axis, perm)
        return state, outputs

    # Carries vary over the pipeline axis (ppermute) AND any axes the input
    # varies over (e.g. dp-sharded batch): adding 0·x unions the two sets.
    def _vary(val):
        # jax>=0.9 renames pvary to pcast(..., to='varying'); support
        # both, and 0.4.x (no varying-axis types) needs no cast at all.
        if hasattr(lax, "pcast"):
            return lax.pcast(val, (axis,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(val, (axis,))
        return val

    zero_like_x = jnp.zeros(mb_shape, x_microbatches.dtype) + \
        x_microbatches[0] * 0
    state0 = _vary(zero_like_x)
    outputs0 = _vary(jnp.zeros_like(x_microbatches) + x_microbatches * 0)
    _, outputs = lax.fori_loop(0, total_steps, body, (state0, outputs0))
    # Results are only valid on the last stage; broadcast so every stage
    # returns them (psum of a one-hot-masked value = ICI broadcast).
    outputs = lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis)
    return outputs


def split_microbatches(x, num_micro: int):
    """(B, ...) → (num_micro, B/num_micro, ...)."""
    B = x.shape[0]
    if B % num_micro:
        raise ValueError(f"batch {B} not divisible by {num_micro} microbatches")
    return x.reshape(num_micro, B // num_micro, *x.shape[1:])


def merge_microbatches(y):
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


def stage_slice_params(params, n_stages: int, stage_axis: int = 0):
    """Utility for tests/single-host: split a stacked-layer param tree into
    per-stage chunks along the layer axis."""
    def split(leaf):
        L = leaf.shape[stage_axis]
        if L % n_stages:
            raise ValueError(f"layer count {L} not divisible by {n_stages} stages")
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(split, params)
