"""Sharding rules: map parameter pytrees to PartitionSpecs.

The reference reaches TP/ZeRO only through external engines
(SURVEY.md §2.4 — FSDP via torch, DeepSpeed configs); here sharding is
native: regex rules over pytree paths produce `PartitionSpec`s, GSPMD
inserts the collectives. ZeRO-3 "falls out": sharding params and optimizer
state over ('fsdp',) is exactly sharded-DP, no wrapper engine needed.
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec


def path_str(path) -> str:
    """Stringify a jax tree path: ('layers', 0, 'attn', 'q') → 'layers/0/attn/q'."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    Mirrors the t5x/flax partitioning idiom (public pattern; not in the
    reference, which has no native sharding system).
    """

    def __init__(self, rules: list[tuple[str, PartitionSpec]],
                 default: PartitionSpec = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, path, leaf=None) -> PartitionSpec:
        s = path_str(path)
        for pat, spec in self.rules:
            if pat.search(s):
                return _clip_spec(spec, leaf)
        return _clip_spec(self.default, leaf)

    def tree_specs(self, tree) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(path, leaf), tree)

    def tree_shardings(self, mesh: Mesh, tree) -> Any:
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(mesh, self.spec_for(path, leaf)),
            tree)


def _clip_spec(spec: PartitionSpec, leaf) -> PartitionSpec:
    """Drop trailing spec entries that exceed the leaf's rank."""
    if leaf is None or not hasattr(leaf, "ndim"):
        return spec
    entries = tuple(spec)
    if len(entries) <= leaf.ndim:
        return spec
    return PartitionSpec(*entries[: leaf.ndim])


# Default rule set for transformer decoders (llama-style naming in
# ray_tpu.models): TP shards attention heads + MLP hidden, FSDP shards the
# other dimension of each matrix (ZeRO). The (vocab, d_model) embedding
# TABLE shards vocab over fsdp and d_model over tp — with vocab on tp,
# the embedding backward needs grad-activations resharded batch→d_model
# ACROSS fsdp, which XLA can only express as a full rematerialization
# ("Involuntary full rematerialization" per step); with d_model on tp the
# reshard is a local slice. The (d_model, vocab) lm_head kernel keeps
# vocab on tp (Megatron column-parallel output; its backward has no such
# pathology — the dryrun compiles warning-free).
TRANSFORMER_RULES = ShardingRules([
    (r"embed/embedding", P("fsdp", "tp")),
    (r"(q_proj|k_proj|v_proj)/kernel", P("fsdp", "tp")),
    (r"o_proj/kernel", P("tp", "fsdp")),
    (r"(gate_proj|up_proj)/kernel", P("fsdp", "tp")),
    (r"down_proj/kernel", P("tp", "fsdp")),
    (r"lm_head/kernel", P("fsdp", "tp")),
    (r"(norm|ln|scale|bias)", P()),
], default=P())


def batch_spec(extra_dims: int = 1) -> PartitionSpec:
    """Global-batch sharding: batch over (dp, fsdp), sequence over sp."""
    return P(("dp", "fsdp"), "sp", *([None] * (extra_dims - 1)))


def shard_tree(mesh: Mesh, tree, rules: ShardingRules):
    """Device-put a pytree with rule-derived shardings."""
    shardings = rules.tree_shardings(mesh, tree)
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)


def with_rules_constraint(tree, rules: ShardingRules):
    """Apply with_sharding_constraint per rule inside jit."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: jax.lax.with_sharding_constraint(
            leaf, rules.spec_for(path, leaf)),
        tree)


def num_params(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "shape"))
