from ray_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshConfig,
    data_axes,
    initialize_multihost,
    make_mesh,
    mesh_axis_size,
)
from ray_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
    stage_slice_params,
)
from ray_tpu.parallel.sharding import (
    TRANSFORMER_RULES,
    P,
    ShardingRules,
    batch_spec,
    num_params,
    shard_tree,
    with_rules_constraint,
)

__all__ = [
    "MeshConfig", "make_mesh", "AXIS_ORDER", "data_axes", "mesh_axis_size",
    "initialize_multihost", "ShardingRules", "TRANSFORMER_RULES", "P",
    "batch_spec", "shard_tree", "with_rules_constraint", "num_params",
    "pipeline_apply", "split_microbatches", "merge_microbatches",
    "stage_slice_params",
]
