"""Job submission: run driver entrypoints on the cluster.

Parity: reference job API (dashboard/modules/job/ — JobSubmissionClient in
dashboard_sdk.py, job_manager.py's per-job supervisor actor, `ray job
submit` CLI at scripts.py:2484). A detached JobSupervisor actor per job
runs the entrypoint as a subprocess on a cluster node, streams its output
to a log file, and records status in the GCS KV store, so the submitting
client can disconnect and later poll status/logs from anywhere.
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass, field

import ray_tpu

# Job lifecycle states (reference: job_submission JobStatus)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

_KV_NS = "job_submission"


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: dict = field(default_factory=dict)
    log_path: str = ""


def _kv_put(key: str, value: dict) -> None:
    import json

    cw = ray_tpu._private.api_internal.get_core_worker()
    cw._run(cw.gcs.call("KVPut", {"ns": _KV_NS, "key": key,
                                  "value": json.dumps(value).encode()}))


def _kv_get(key: str) -> dict | None:
    import json

    cw = ray_tpu._private.api_internal.get_core_worker()
    resp = cw._run(cw.gcs.call("KVGet", {"ns": _KV_NS, "key": key}))
    v = resp.get("value")
    return json.loads(bytes(v).decode()) if v else None


def _kv_keys() -> list[str]:
    cw = ray_tpu._private.api_internal.get_core_worker()
    resp = cw._run(cw.gcs.call("KVKeys", {"ns": _KV_NS, "prefix": ""}))
    return [k if isinstance(k, str) else bytes(k).decode()
            for k in resp.get("keys", [])]


@ray_tpu.remote
class JobSupervisor:
    """Detached actor owning one job's subprocess (reference:
    job_manager.py JobSupervisor)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: dict | None, log_path: str, metadata: dict):
        import json
        import subprocess
        import threading

        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self._stopped = False
        env = dict(os.environ)
        env.update(env_vars or {})
        self._record(RUNNING, start_time=time.time(), metadata=metadata)
        self._logf = open(log_path, "wb", buffering=0)
        self._proc = subprocess.Popen(
            entrypoint, shell=True, stdout=self._logf,
            stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _record(self, status: str, **extra) -> None:
        info = _kv_get(self.submission_id) or {}
        info.update({"submission_id": self.submission_id,
                     "entrypoint": self.entrypoint,
                     "status": status, "log_path": self.log_path}, **{})
        info.update(extra)
        _kv_put(self.submission_id, info)

    def _wait(self) -> None:
        code = self._proc.wait()
        if self._stopped:
            self._record(STOPPED, end_time=time.time(),
                         message="stopped by user")
        elif code == 0:
            self._record(SUCCEEDED, end_time=time.time())
        else:
            self._record(FAILED, end_time=time.time(),
                         message=f"entrypoint exited with code {code}")
        self._logf.close()

    def stop(self) -> bool:
        import signal

        self._stopped = True
        try:
            os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            return False
        return True

    def running(self) -> bool:
        return self._proc.poll() is None

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Parity: reference JobSubmissionClient (REST in the reference; actor
    RPC here — same method surface)."""

    def __init__(self, address: str | None = None):
        if not ray_tpu.is_initialized():
            if address:
                raise RuntimeError(
                    "connect with ray_tpu.init(address=...) before creating "
                    "a JobSubmissionClient")
            ray_tpu.init()
        self._log_dir = os.path.join("/tmp", "ray_tpu", "job_logs")
        os.makedirs(self._log_dir, exist_ok=True)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: dict | None = None,
                   submission_id: str | None = None,
                   metadata: dict | None = None) -> str:
        sid = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if _kv_get(sid) is not None:
            raise ValueError(f"job {sid!r} already exists")
        log_path = os.path.join(self._log_dir, f"{sid}.log")
        env_vars = (runtime_env or {}).get("env_vars")
        _kv_put(sid, {"submission_id": sid, "entrypoint": entrypoint,
                      "status": PENDING, "log_path": log_path,
                      "metadata": metadata or {}})
        JobSupervisor.options(
            name=f"_job_supervisor:{sid}", lifetime="detached",
            namespace="_job_submission").remote(
            sid, entrypoint, env_vars, log_path, metadata or {})
        return sid

    def get_job_status(self, submission_id: str) -> str:
        info = _kv_get(submission_id)
        if info is None:
            raise ValueError(f"job {submission_id!r} not found")
        return info["status"]

    def get_job_info(self, submission_id: str) -> JobInfo:
        info = _kv_get(submission_id)
        if info is None:
            raise ValueError(f"job {submission_id!r} not found")
        return JobInfo(**{k: v for k, v in info.items()
                          if k in JobInfo.__dataclass_fields__})

    def get_job_logs(self, submission_id: str) -> str:
        info = _kv_get(submission_id)
        if info is None:
            raise ValueError(f"job {submission_id!r} not found")
        path = info.get("log_path")
        if not path or not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def list_jobs(self) -> list[JobInfo]:
        out = []
        for key in _kv_keys():
            info = _kv_get(key)
            if info:
                out.append(JobInfo(**{k: v for k, v in info.items()
                                      if k in JobInfo.__dataclass_fields__}))
        return sorted(out, key=lambda j: j.start_time)

    def stop_job(self, submission_id: str) -> bool:
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor:{submission_id}",
                                    namespace="_job_submission")
        except Exception:
            return False
        return ray_tpu.get(sup.stop.remote())

    def delete_job(self, submission_id: str) -> bool:
        info = _kv_get(submission_id)
        if info is None:
            return False
        if info["status"] in (PENDING, RUNNING):
            raise RuntimeError("stop the job before deleting it")
        cw = ray_tpu._private.api_internal.get_core_worker()
        cw._run(cw.gcs.call("KVDel", {"ns": _KV_NS, "key": submission_id}))
        return True

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        while True:
            status = self.get_job_status(submission_id)
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {submission_id} still {status} after {timeout}s")
            time.sleep(min(0.2, max(0.0, deadline - time.monotonic())))


__all__ = ["JobSubmissionClient", "JobInfo", "PENDING", "RUNNING",
           "SUCCEEDED", "FAILED", "STOPPED"]
