"""Trial schedulers: FIFO, ASHA, PBT.

Parity: reference python/ray/tune/schedulers/ — ASHA
(async_hyperband.py:19: asynchronous successive halving with rungs at
reduction_factor intervals) and PBT (pbt.py:222; exploit at :881 clones a
better trial's checkpoint and perturbs hyperparams).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Any

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"      # PBT: restart from better trial's checkpoint
REALLOCATE = "REALLOCATE"  # ResourceChanging: restart with new resources


class FIFOScheduler:
    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        return CONTINUE

    def exploit_target(self, trial, trials):
        return None


class ASHAScheduler:
    """Async successive halving: at each rung, trials below the top
    1/reduction_factor quantile of completed rung results stop early."""

    def __init__(self, *, metric: str, mode: str = "max", max_t: int = 100,
                 grace_period: int = 1, reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> list of recorded metric values
        self.rungs: dict[int, list[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        if iteration >= self.max_t:
            return STOP
        for m in self.milestones:
            if iteration == m:
                sign = metric_value if self.mode == "max" else -metric_value
                recorded = self.rungs.setdefault(m, [])
                recorded.append(sign)
                k = max(1, len(recorded) // self.rf)
                top_k = sorted(recorded, reverse=True)[:k]
                if sign < top_k[-1]:
                    return STOP
        return CONTINUE

    def exploit_target(self, trial, trials):
        return None


class PopulationBasedTraining:
    """PBT: every perturbation_interval iterations, bottom-quantile trials
    clone a top-quantile trial's checkpoint and perturb hyperparams."""

    def __init__(self, *, metric: str, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int | None = None):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_probability = resample_probability
        self.rng = random.Random(seed)

    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        trial.last_metric = metric_value
        if iteration > 0 and iteration % self.interval == 0:
            return EXPLOIT
        return CONTINUE

    def exploit_target(self, trial, trials):
        """If `trial` is bottom-quantile, return a top-quantile trial to
        clone from; else None (keep training)."""
        scored = [t for t in trials if t.last_metric is not None]
        if len(scored) < 2:
            return None
        key = (lambda t: t.last_metric) if self.mode == "max" \
            else (lambda t: -t.last_metric)
        ranked = sorted(scored, key=key, reverse=True)
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = ranked[-k:]
        top = ranked[:k]
        if trial in bottom and trial not in top:
            return self.rng.choice(top)
        return None

    def perturb(self, config: dict) -> dict:
        """Mutate hyperparams (reference: pbt.py explore)."""
        from ray_tpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_probability:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    out[key] = self.rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                cur = out.get(key)
                if isinstance(cur, (int, float)) and not isinstance(cur, bool):
                    factor = self.rng.choice([0.8, 1.2])
                    out[key] = type(cur)(cur * factor)
                elif isinstance(spec, list) and cur in spec:
                    idx = spec.index(cur)
                    shift = self.rng.choice([-1, 1])
                    out[key] = spec[max(0, min(len(spec) - 1, idx + shift))]
        return out


class PB2(PopulationBasedTraining):
    """Population-based bandits (reference: tune/schedulers/pb2.py).

    Exploitation is PBT's (bottom-quantile trials clone a top trial's
    checkpoint); EXPLORATION replaces random perturbation with a
    GP-UCB model fit to (hyperparameters -> recent reward improvement)
    observations from the whole population, selecting the new
    hyperparameters inside `hyperparam_bounds` that maximize predicted
    improvement plus an exploration bonus. The GP is an RBF-kernel
    ridge regression over normalized hyperparameters — closed form, no
    external dependency."""

    def __init__(self, *, metric: str, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: dict,
                 quantile_fraction: float = 0.25,
                 kappa: float = 1.0, n_candidates: int = 64,
                 seed: int | None = None):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds: "
                             "{key: [low, high]}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = kappa
        self.n_candidates = n_candidates
        self._last_metric: dict[str, float] = {}
        self._history: list[tuple[list[float], float]] = []

    def _vec(self, config: dict) -> list[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        prev = self._last_metric.get(trial.trial_id)
        if prev is not None:
            sign = 1.0 if self.mode == "max" else -1.0
            self._history.append((self._vec(trial.config),
                                  sign * (metric_value - prev)))
            self._history = self._history[-200:]  # bounded model data
        self._last_metric[trial.trial_id] = metric_value
        return super().on_result(trial, metric_value, iteration)

    def perturb(self, config: dict) -> dict:
        """Model-guided explore step (replaces PBT's random factors)."""
        import numpy as np

        out = dict(config)
        keys = list(self.bounds)
        if len(self._history) < 4:
            # Cold start: uniform within bounds.
            for k in keys:
                lo, hi = self.bounds[k]
                out[k] = type(config.get(k, lo))(self.rng.uniform(lo, hi))
            return out
        X = np.asarray([x for x, _ in self._history])
        y = np.asarray([d for _, d in self._history])
        y = (y - y.mean()) / (y.std() + 1e-8)
        ell, lam = 0.2, 0.1
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-d2 / (2 * ell * ell)) + lam * np.eye(len(X))
        Kinv_y = np.linalg.solve(K, y)
        Kinv = np.linalg.inv(K)
        cands = np.asarray([[self.rng.random() for _ in keys]
                            for _ in range(self.n_candidates)])
        kstar = np.exp(-((cands[:, None, :] - X[None, :, :]) ** 2
                         ).sum(-1) / (2 * ell * ell))
        mu = kstar @ Kinv_y
        var = np.clip(1.0 - np.einsum("ci,ij,cj->c", kstar, Kinv, kstar),
                      1e-9, None)
        best = cands[int(np.argmax(mu + self.kappa * np.sqrt(var)))]
        for k, u in zip(keys, best):
            lo, hi = self.bounds[k]
            val = lo + float(u) * (hi - lo)
            cur = config.get(k, lo)
            out[k] = type(cur)(val) if isinstance(cur, (int, float)) \
                and not isinstance(cur, bool) else val
        return out


class HyperBandScheduler:
    """HyperBand (Li et al. 2017): several successive-halving brackets with
    staggered starting budgets, so some trials get long uninterrupted runs
    while others are aggressively halved (reference:
    tune/schedulers/hyperband.py; this is the async formulation — each
    bracket behaves like ASHA with grace_period scaled by rf^s)."""

    def __init__(self, *, metric: str, mode: str = "max", max_t: int = 81,
                 reduction_factor: int = 3):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        self.s_max = max(1, int(math.log(max_t) / math.log(reduction_factor)))
        # bracket s -> {milestone -> recorded signed metrics}
        self.brackets: list[dict[int, list[float]]] = [
            {} for _ in range(self.s_max)]
        self._assignment: dict[Any, int] = {}
        self._next_bracket = 0

    def _bracket_of(self, trial) -> int:
        tid = trial.trial_id
        if tid not in self._assignment:
            self._assignment[tid] = self._next_bracket
            self._next_bracket = (self._next_bracket + 1) % self.s_max
        return self._assignment[tid]

    def _milestones(self, s: int) -> list[int]:
        out, t = [], self.rf ** s
        while t < self.max_t:
            out.append(t)
            t *= self.rf
        return out

    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        if iteration >= self.max_t:
            return STOP
        s = self._bracket_of(trial)
        for m in self._milestones(s):
            if iteration == m:
                sign = metric_value if self.mode == "max" else -metric_value
                recorded = self.brackets[s].setdefault(m, [])
                recorded.append(sign)
                k = max(1, len(recorded) // self.rf)
                top_k = sorted(recorded, reverse=True)[:k]
                if sign < top_k[-1]:
                    return STOP
        return CONTINUE

    def exploit_target(self, trial, trials):
        return None


class ResourceChangingScheduler:
    """Wraps a base scheduler and grows/shrinks a trial's resources
    mid-run (reference: tune/schedulers/resource_changing_scheduler.py).
    `resources_allocation_function(trial, metric_value, iteration)
    -> dict | None` returns the new resource dict (None = keep current);
    a different allocation restarts the trial from its last checkpoint
    with those resources."""

    def __init__(self, base_scheduler=None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        self.alloc_fn = resources_allocation_function
        self.metric = getattr(self.base, "metric", None)
        self.mode = getattr(self.base, "mode", "max")

    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        decision = self.base.on_result(trial, metric_value, iteration)
        if decision != CONTINUE or self.alloc_fn is None:
            return decision
        new_res = self.alloc_fn(trial, metric_value, iteration)
        if new_res and new_res != getattr(trial, "resources", None):
            trial.pending_resources = dict(new_res)
            return REALLOCATE
        return CONTINUE

    def exploit_target(self, trial, trials):
        return self.base.exploit_target(trial, trials)

    def perturb(self, config: dict) -> dict:
        return self.base.perturb(config) if hasattr(self.base, "perturb") \
            else dict(config)


class MedianStoppingRule:
    """Stop trials whose running mean falls below the median of others
    (reference: schedulers/median_stopping_rule.py)."""

    def __init__(self, *, metric: str, mode: str = "max",
                 grace_period: int = 4):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.histories: dict[Any, list[float]] = {}

    def on_result(self, trial, metric_value: float, iteration: int) -> str:
        sign = metric_value if self.mode == "max" else -metric_value
        self.histories.setdefault(trial.trial_id, []).append(sign)
        if iteration < self.grace:
            return CONTINUE
        means = [sum(h) / len(h) for tid, h in self.histories.items()
                 if tid != trial.trial_id and h]
        if not means:
            return CONTINUE
        means.sort()
        median = means[len(means) // 2]
        mine = self.histories[trial.trial_id]
        if sum(mine) / len(mine) < median:
            return STOP
        return CONTINUE

    def exploit_target(self, trial, trials):
        return None
