"""Tuner + trial controller.

Parity: reference python/ray/tune/tuner.py:59 (Tuner) and
tune/execution/tune_controller.py (the event loop managing trials as
actors). Trials run as TrainWorker actors (the same session/report
machinery Train uses — the reference likewise runs trainers as Tune
trials, base_trainer.py:877); the controller polls reports, applies the
scheduler (ASHA early-stopping, PBT exploit/explore with checkpoint
cloning), and collects a ResultGrid.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.worker_group import TrainWorker
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants


@dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    scheduler: Any = None
    search_alg: Any = None   # a tune.search.Searcher; None = expand upfront
    seed: int | None = None


class Trial:
    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config
        self.status = "PENDING"
        self.actor = None
        self.iteration = 0
        self.last_metric: float | None = None
        self.metrics_history: list[dict] = []
        self.checkpoint: Checkpoint | None = None
        self.error: str | None = None
        self.resources: dict | None = None       # None = controller default
        self.pending_resources: dict | None = None  # set by REALLOCATE

    def best_metric(self, metric: str, mode: str):
        vals = [m[metric] for m in self.metrics_history if metric in m]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)


@dataclass
class TrialResult:
    config: dict
    metrics: dict
    checkpoint: Checkpoint | None
    error: str | None
    metrics_history: list = field(default_factory=list)


class ResultGrid:
    def __init__(self, results: list[TrialResult], metric: str | None,
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required")
        scored = [r for r in self._results
                  if r.error is None and metric in r.metrics]
        if not scored:
            raise ValueError("no successful trials with metric " + metric)
        key = lambda r: r.metrics[metric]
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([{**r.metrics, **{f"config/{k}": v
                                              for k, v in r.config.items()}}
                             for r in self._results])

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]


class Tuner:
    def __init__(self, trainable: Callable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: RunConfig | None = None,
                 resources_per_trial: dict | None = None,
                 _restored_trials: list | None = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._resources = resources_per_trial or {"CPU": 1}
        self._restored_trials = _restored_trials

    def _experiment_dir(self) -> str | None:
        rc = self.run_config
        if rc.storage_path is None:
            return None
        import os

        d = os.path.join(rc.storage_path, rc.name or "tune_experiment")
        os.makedirs(d, exist_ok=True)
        return d

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                *, resume_errored: bool = False) -> "Tuner":
        """Resume an interrupted experiment from its directory (reference:
        Tuner.restore + tune/execution/experiment_state.py).  Finished
        trials keep their recorded results; unfinished (and optionally
        errored) trials re-run, restoring from their last checkpoint."""
        import json
        import os

        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        trials = []
        for ts in state["trials"]:
            t = Trial(ts["trial_id"], ts["config"])
            t.status = ts["status"]
            t.iteration = ts["iteration"]
            t.metrics_history = ts["metrics_history"]
            t.error = ts.get("error")
            if ts.get("checkpoint_path"):
                t.checkpoint = Checkpoint(ts["checkpoint_path"])
            if t.status in ("PENDING", "RUNNING", "PAUSED") or \
                    (resume_errored and t.status == "ERROR"):
                t.status = "PENDING"
                t.error = None
            trials.append(t)
        tc = TuneConfig(**state.get("tune_config", {}))
        from ray_tpu._private import serialization as _ser

        sched_path = os.path.join(path, "scheduler.pkl")
        if os.path.exists(sched_path):
            with open(sched_path, "rb") as f:
                tc.scheduler = _ser.loads_func(f.read())
        searcher_path = os.path.join(path, "searcher.pkl")
        if os.path.exists(searcher_path):
            with open(searcher_path, "rb") as f:
                tc.search_alg = _ser.loads_func(f.read())
        rc = RunConfig(storage_path=os.path.dirname(path.rstrip("/")),
                       name=os.path.basename(path.rstrip("/")))
        return cls(trainable, param_space=state.get("param_space", {}),
                   tune_config=tc, run_config=rc,
                   resources_per_trial=state.get("resources"),
                   _restored_trials=trials)

    def fit(self) -> ResultGrid:
        searcher = self.tune_config.search_alg
        if self._restored_trials is not None:
            trials = self._restored_trials
        elif searcher is not None:
            trials = []  # created lazily from searcher.suggest()
        else:
            cfgs = generate_variants(self._param_space,
                                     self.tune_config.num_samples,
                                     self.tune_config.seed)
            trials = [Trial(f"trial_{i:04d}_{uuid.uuid4().hex[:6]}", c)
                      for i, c in enumerate(cfgs)]
        scheduler = self.tune_config.scheduler or FIFOScheduler()
        metric = self.tune_config.metric
        default_conc = max(1, len(trials)) if searcher is None else 2
        max_conc = self.tune_config.max_concurrent_trials or default_conc
        controller = _TuneController(
            self._trainable, trials, scheduler, metric,
            self.tune_config.mode, max_conc, self._resources,
            searcher=searcher,
            experiment_dir=self._experiment_dir(),
            experiment_state={
                "param_space": self._param_space,
                "tune_config": {
                    "metric": metric, "mode": self.tune_config.mode,
                    "num_samples": self.tune_config.num_samples,
                    "max_concurrent_trials":
                        self.tune_config.max_concurrent_trials,
                    "seed": self.tune_config.seed},
                "resources": self._resources})
        controller.run()
        results = [TrialResult(
            config=t.config,
            metrics=t.metrics_history[-1] if t.metrics_history else {},
            checkpoint=t.checkpoint, error=t.error,
            metrics_history=t.metrics_history) for t in trials]
        return ResultGrid(results, metric, self.tune_config.mode)


class _TuneController:
    """Polling event loop (reference: tune_controller.py)."""

    def __init__(self, trainable, trials, scheduler, metric, mode,
                 max_concurrent, resources, searcher=None,
                 experiment_dir: str | None = None,
                 experiment_state: dict | None = None):
        self.trainable_blob = serialization.dumps_func(trainable)
        self.trials: list[Trial] = trials
        self.scheduler = scheduler
        self.metric = metric
        self.mode = mode
        self.max_concurrent = max_concurrent
        self.resources = resources
        self.searcher = searcher
        self.experiment_dir = experiment_dir
        self.experiment_state = experiment_state or {}

    def _notify_searcher(self, trial: Trial) -> None:
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id, trial.config,
                                            trial.last_metric)

    def _next_from_searcher(self) -> Trial | None:
        if self.searcher is None:
            return None
        tid = f"trial_{len(self.trials):04d}_{uuid.uuid4().hex[:6]}"
        cfg = self.searcher.suggest(tid)
        if cfg is None:
            return None
        t = Trial(tid, cfg)
        self.trials.append(t)
        return t

    def _save_experiment_state(self, force: bool = False):
        """Durable experiment snapshot for Tuner.restore, throttled to one
        write per few seconds (reference: experiment_state.py time-based
        periodic checkpointing — per-tick writes would put O(total
        reports) of JSON I/O in the scheduling hot loop)."""
        if self.experiment_dir is None:
            return
        now = time.monotonic()
        if not force and now - getattr(self, "_last_state_save", 0.0) < 5.0:
            return
        self._last_state_save = now
        import json
        import os

        def _plain(x):
            """JSON-safe: numpy scalars → python numbers (default=str
            would silently stringify metrics and break get_best_result
            comparisons after a restore)."""
            if hasattr(x, "item") and not isinstance(x, (str, bytes)):
                try:
                    return x.item()
                except Exception:
                    pass
            return str(x)

        state = dict(self.experiment_state)
        state["trials"] = [{
            "trial_id": t.trial_id, "config": t.config, "status": t.status,
            "iteration": t.iteration, "metrics_history": t.metrics_history,
            "error": t.error,
            "checkpoint_path": t.checkpoint.path if t.checkpoint else None,
        } for t in self.trials]
        tmp = os.path.join(self.experiment_dir, "experiment_state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, default=_plain)
        os.replace(tmp, os.path.join(self.experiment_dir,
                                     "experiment_state.json"))
        # The scheduler (ASHA rungs, PBT state) and searcher (TPE
        # observations) ride along as pickles so restore resumes under the
        # SAME policy with its accumulated state.
        sched_blob = serialization.dumps_func(self.scheduler)
        with open(os.path.join(self.experiment_dir, "scheduler.pkl"),
                  "wb") as f:
            f.write(sched_blob)
        if self.searcher is not None:
            with open(os.path.join(self.experiment_dir, "searcher.pkl"),
                      "wb") as f:
                f.write(serialization.dumps_func(self.searcher))

    def _start_trial(self, trial: Trial, restore_from: Checkpoint | None = None):
        res = trial.resources or self.resources
        if isinstance(res, (list, tuple)):
            # PlacementGroupFactory-style bundles (reference:
            # tune/execution/placement_groups.py — a PG per trial): the
            # trial actor takes bundle 0; the rest stay reserved for the
            # trainable's own sub-workers via config["_trial_pg"].
            from ray_tpu.util.placement_group import (placement_group,
                                                      remove_placement_group)

            trial.pg = placement_group([dict(b) for b in res],
                                       strategy="PACK")
            try:
                ray_tpu.get(trial.pg.ready(), timeout=120)
            except Exception:
                # Unschedulable (cluster too small / oversubscribed by
                # concurrent trials): release the reservation — a leaked
                # PG would starve every later trial.
                remove_placement_group(trial.pg)
                trial.pg = None
                raise
            b0 = res[0]
            opts = {"num_cpus": b0.get("CPU", 0),
                    "resources": {k: v for k, v in b0.items() if k != "CPU"},
                    "placement_group": trial.pg,
                    "placement_group_bundle_index": 0}
        else:
            trial.pg = None
            opts = {"num_cpus": res.get("CPU", 1),
                    "resources": {k: v for k, v in res.items() if k != "CPU"}}
        trial.actor = TrainWorker.options(**opts).remote(0, 1, {})
        cfg = dict(trial.config)
        if restore_from is not None:
            cfg["_checkpoint_path"] = restore_from.path
        if trial.pg is not None:
            # The trainable places its own sub-workers into the reserved
            # bundles (reference: trials run inside their PG by default).
            cfg["_trial_pg"] = trial.pg
        ray_tpu.get(trial.actor.run.remote(self.trainable_blob, cfg))
        trial.status = "RUNNING"

    def _stop_trial(self, trial: Trial, status: str):
        trial.status = status
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if getattr(trial, "pg", None) is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None

    def run(self):
        # Restored TERMINATED/ERROR trials keep their results; only
        # PENDING ones (fresh or reset by Tuner.restore) run.
        pending = [t for t in self.trials if t.status == "PENDING"]
        running: list[Trial] = []
        searcher_done = False
        self._save_experiment_state()
        while True:
            while len(running) < self.max_concurrent:
                if pending:
                    t = pending.pop(0)
                elif not searcher_done:
                    t = self._next_from_searcher()
                    if t is None:
                        searcher_done = True
                        break
                else:
                    break
                # A restored trial resumes from its last checkpoint. A
                # start failure (unschedulable PG, worker spawn) fails
                # THAT trial; it must not abort the experiment and lose
                # every other trial's results.
                try:
                    self._start_trial(t, restore_from=t.checkpoint)
                except Exception as e:  # noqa: BLE001
                    t.error = f"trial start failed: {type(e).__name__}: {e}"
                    self._stop_trial(t, "ERROR")
                    self._notify_searcher(t)
                    continue
                running.append(t)
            if not running and not pending:
                break
            polls = ray_tpu.get([t.actor.poll.remote() for t in running],
                                timeout=300)
            for trial, p in zip(list(running), polls):
                decision = CONTINUE
                for rep in p["reports"]:
                    m = rep["metrics"]
                    trial.metrics_history.append(m)
                    trial.iteration += 1
                    if rep.get("checkpoint_path"):
                        trial.checkpoint = Checkpoint(rep["checkpoint_path"])
                    if self.metric and self.metric in m:
                        trial.last_metric = m[self.metric]
                        decision = self.scheduler.on_result(
                            trial, m[self.metric], trial.iteration)
                        if decision != CONTINUE:
                            break
                if p["done"]:
                    trial.error = p["error"]
                    self._stop_trial(trial,
                                     "ERROR" if p["error"] else "TERMINATED")
                    running.remove(trial)
                    self._notify_searcher(trial)
                elif decision == STOP:
                    self._stop_trial(trial, "TERMINATED")
                    running.remove(trial)
                    self._notify_searcher(trial)
                elif decision == EXPLOIT:
                    target = self.scheduler.exploit_target(trial, self.trials)
                    if target is not None and target.checkpoint is not None:
                        # PBT exploit: clone checkpoint + perturbed config.
                        self._stop_trial(trial, "PAUSED")
                        trial.config = self.scheduler.perturb(target.config)
                        self._start_trial(trial, restore_from=target.checkpoint)
                elif decision == sched_mod.REALLOCATE and trial.checkpoint:
                    # ResourceChanging: restart from the last checkpoint
                    # with the scheduler's new allocation.
                    self._stop_trial(trial, "PAUSED")
                    trial.resources = trial.pending_resources
                    trial.pending_resources = None
                    self._start_trial(trial, restore_from=trial.checkpoint)
            self._save_experiment_state()
            if running or pending:
                time.sleep(0.05)
        # Final snapshot must not be lost to the throttle window.
        self._save_experiment_state(force=True)
