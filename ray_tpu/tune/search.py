"""Search spaces + variant generation.

Parity: reference python/ray/tune/search/ — sample spaces
(tune.uniform/loguniform/choice/randint), grid_search, and
BasicVariantGenerator (search/basic_variant.py) expanding param_space
dicts into trial configs.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]

    def sample(self, rng: random.Random):
        return self.sampler(rng)


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))))


def randint(low: int, high: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high))


def choice(options: list) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts))


def quniform(low: float, high: float, q: float) -> Domain:
    return Domain(lambda rng: round(rng.uniform(low, high) / q) * q)


@dataclass
class GridSearch:
    values: list


def grid_search(values: list) -> GridSearch:
    return GridSearch(list(values))


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: int | None = None) -> list[dict]:
    """Expand grid axes (cross product) × num_samples random draws.

    Matches the reference semantics: num_samples multiplies the grid
    (basic_variant.py)."""
    rng = random.Random(seed)
    grid_axes: list[tuple[str, list]] = []

    def find_grids(prefix: str, node):
        if isinstance(node, GridSearch):
            grid_axes.append((prefix, node.values))
        elif isinstance(node, dict):
            for k, v in node.items():
                find_grids(f"{prefix}.{k}" if prefix else k, v)

    find_grids("", param_space)

    def grid_combos(axes):
        if not axes:
            return [{}]
        key, values = axes[0]
        rest = grid_combos(axes[1:])
        return [{**r, key: v} for v in values for r in rest]

    def resolve(node, overrides: dict, prefix: str = ""):
        if isinstance(node, GridSearch):
            return overrides[prefix]
        if isinstance(node, Domain):
            return node.sample(rng)
        if isinstance(node, dict):
            return {k: resolve(v, overrides, f"{prefix}.{k}" if prefix else k)
                    for k, v in node.items()}
        if callable(node) and not isinstance(node, type):
            return node()
        return node

    variants = []
    for _ in range(num_samples):
        for combo in grid_combos(grid_axes):
            variants.append(resolve(param_space, combo))
    return variants
