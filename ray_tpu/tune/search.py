"""Search spaces + variant generation.

Parity: reference python/ray/tune/search/ — sample spaces
(tune.uniform/loguniform/choice/randint), grid_search, and
BasicVariantGenerator (search/basic_variant.py) expanding param_space
dicts into trial configs.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]
    # Bounds metadata (None when the domain isn't an ordered range) — lets
    # model-based searchers keep resampled candidates inside the space.
    low: float | None = None
    high: float | None = None
    integer: bool = False
    # Scale/shape metadata for external optimizers (OptunaSearch maps
    # log -> suggest_float(log=True), options -> suggest_categorical).
    log: bool = False
    options: list | None = None

    def sample(self, rng: random.Random):
        return self.sampler(rng)

    def clamp(self, value):
        """Project a (possibly out-of-range) candidate back into bounds."""
        if self.low is not None:
            value = max(self.low, min(self.high, value))
        if self.integer:
            value = int(round(value))
            if self.high is not None:
                # randint's high is exclusive, matching the sampler.
                value = min(value, int(self.high) - 1)
        return value


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high), low=low, high=high)


def loguniform(low: float, high: float) -> Domain:
    return Domain(
        lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))),
        low=low, high=high, log=True)


def randint(low: int, high: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high), low=low, high=high,
                  integer=True)


def choice(options: list) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts), options=opts)


def quniform(low: float, high: float, q: float) -> Domain:
    return Domain(lambda rng: round(rng.uniform(low, high) / q) * q,
                  low=low, high=high)


@dataclass
class GridSearch:
    values: list


def grid_search(values: list) -> GridSearch:
    return GridSearch(list(values))


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: int | None = None) -> list[dict]:
    """Expand grid axes (cross product) × num_samples random draws.

    Matches the reference semantics: num_samples multiplies the grid
    (basic_variant.py)."""
    rng = random.Random(seed)
    grid_axes: list[tuple[str, list]] = []

    def find_grids(prefix: str, node):
        if isinstance(node, GridSearch):
            grid_axes.append((prefix, node.values))
        elif isinstance(node, dict):
            for k, v in node.items():
                find_grids(f"{prefix}.{k}" if prefix else k, v)

    find_grids("", param_space)

    def grid_combos(axes):
        if not axes:
            return [{}]
        key, values = axes[0]
        rest = grid_combos(axes[1:])
        return [{**r, key: v} for v in values for r in rest]

    def resolve(node, overrides: dict, prefix: str = ""):
        if isinstance(node, GridSearch):
            return overrides[prefix]
        if isinstance(node, Domain):
            return node.sample(rng)
        if isinstance(node, dict):
            return {k: resolve(v, overrides, f"{prefix}.{k}" if prefix else k)
                    for k, v in node.items()}
        if callable(node) and not isinstance(node, type):
            return node()
        return node

    variants = []
    for _ in range(num_samples):
        for combo in grid_combos(grid_axes):
            variants.append(resolve(param_space, combo))
    return variants


# ---------------------------------------------------------------------------
# Searchers: sequential config suggestion informed by completed trials
# (parity: reference tune/search/searcher.py protocol + the model-based
# algorithms wired through it — Optuna/HyperOpt/BOHB. Those engines aren't
# vendored; TPESearcher below is a native tree-structured-Parzen-style
# implementation of the same suggest/observe contract.)
# ---------------------------------------------------------------------------


def _flatten(space: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in space.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _unflatten(flat: dict) -> dict:
    out: dict = {}
    for key, v in flat.items():
        parts = key.split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


class Searcher:
    """Suggest/observe protocol (reference: tune/search/searcher.py)."""

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, config: dict,
                          metric_value: float | None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Random/grid sampling through the Searcher protocol (reference:
    tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: int | None = None):
        self._variants = generate_variants(param_space, num_samples, seed)
        self._next = 0

    def suggest(self, trial_id: str) -> dict | None:
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen estimator over Domain leaves (Bergstra et
    al. 2011 — the model behind HyperOpt/BOHB): completed trials split
    into good/bad by metric quantile; candidates are drawn from a kernel
    density around good points and ranked by the good/bad density ratio.
    Non-Domain leaves pass through as constants."""

    def __init__(self, param_space: dict, *, metric: str, mode: str = "max",
                 num_samples: int = 32, n_initial: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int | None = None):
        assert mode in ("max", "min")
        self.space = _flatten(param_space)
        self.metric = metric
        self.mode = mode
        self.max_suggestions = num_samples
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._suggested = 0
        # (flat_config, signed_metric) for completed trials
        self.observations: list[tuple[dict, float]] = []

    # -- observation --

    def on_trial_complete(self, trial_id: str, config: dict,
                          metric_value: float | None) -> None:
        if metric_value is None:
            return
        sign = metric_value if self.mode == "max" else -metric_value
        self.observations.append((_flatten(config), sign))

    # -- suggestion --

    def _random_flat(self) -> dict:
        out = {}
        for k, v in self.space.items():
            if isinstance(v, Domain):
                out[k] = v.sample(self.rng)
            elif isinstance(v, GridSearch):
                out[k] = self.rng.choice(v.values)
            else:
                out[k] = v
        return out

    def _kde_logpdf(self, points: list[float], x: float, bw: float) -> float:
        if not points:
            return -1e9
        acc = 0.0
        for p in points:
            acc += math.exp(-0.5 * ((x - p) / bw) ** 2)
        return math.log(acc / len(points) + 1e-12)

    def suggest(self, trial_id: str) -> dict | None:
        if self._suggested >= self.max_suggestions:
            return None
        self._suggested += 1
        if len(self.observations) < self.n_initial:
            return _unflatten(self._random_flat())

        ranked = sorted(self.observations, key=lambda o: o[1], reverse=True)
        n_good = max(1, int(len(ranked) * self.gamma))
        good, bad = ranked[:n_good], ranked[n_good:]

        numeric = [k for k, v in self.space.items()
                   if isinstance(v, Domain)
                   and isinstance(good[0][0].get(k), (int, float))
                   and not isinstance(good[0][0].get(k), bool)]
        best_cfg, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            cand = self._random_flat()
            # Resample numeric dims around good observations (Parzen draw),
            # projected back into the Domain's bounds (a gauss draw around
            # a small loguniform anchor would otherwise go negative).
            for k in numeric:
                vals = [o[0][k] for o in good if k in o[0]]
                if vals:
                    anchor = self.rng.choice(vals)
                    spread = (max(vals) - min(vals)) or abs(anchor) or 1.0
                    draw = self.rng.gauss(anchor, 0.3 * spread) \
                        if isinstance(vals[0], float) \
                        else self.rng.gauss(anchor, max(1.0, 0.3 * spread))
                    dom = self.space[k]
                    cand[k] = type(vals[0])(dom.clamp(draw))
            score = 0.0
            for k in numeric:
                g = [o[0][k] for o in good if k in o[0]]
                b = [o[0][k] for o in bad if k in o[0]]
                bw = ((max(g) - min(g)) or abs(g[0]) or 1.0) * 0.3 if g else 1.0
                score += self._kde_logpdf(g, cand[k], bw) \
                    - self._kde_logpdf(b, cand[k], bw)
            if score > best_score:
                best_score, best_cfg = score, cand
        return _unflatten(best_cfg)


class ExternalSearcher(Searcher):
    """Adapter surface for third-party ask/tell optimizers (reference:
    tune/search/ wraps Optuna/HyperOpt/Ax behind Searcher). Any object
    pair (ask() -> config | None, tell(config, value)) plugs in; the
    Tuner only ever sees the Searcher protocol."""

    def __init__(self, ask, tell=None):
        self._ask = ask
        self._tell = tell

    def suggest(self, trial_id: str) -> dict | None:
        return self._ask()

    def on_trial_complete(self, trial_id: str, config: dict,
                          metric_value: float | None) -> None:
        if self._tell is not None and metric_value is not None:
            self._tell(config, metric_value)


class OptunaSearch(ExternalSearcher):
    """Optuna-backed searcher (reference: tune/search/optuna/). Requires
    the optuna package; this image does not bundle it, so construction
    raises ImportError with a clear message when absent."""

    def __init__(self, param_space: dict, *, metric: str, mode: str = "max",
                 num_samples: int = 32, seed: int | None = None):
        try:
            import optuna
        except ImportError as e:  # pragma: no cover - dep not in image
            raise ImportError(
                "OptunaSearch requires the 'optuna' package") from e
        space = _flatten(param_space)
        study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=optuna.samplers.TPESampler(seed=seed))
        self._budget = num_samples
        self._asked = 0
        self._rng = random.Random(seed)
        self._trials: dict[int, Any] = {}

        def ask():
            if self._asked >= self._budget:
                return None
            self._asked += 1
            t = study.ask()
            cfg = {}
            for k, v in space.items():
                if isinstance(v, Domain) and v.options is not None:
                    cfg[k] = t.suggest_categorical(k, v.options)
                elif isinstance(v, Domain) and v.low is not None:
                    if v.integer:
                        cfg[k] = t.suggest_int(k, int(v.low),
                                               int(v.high) - 1)
                    else:
                        cfg[k] = t.suggest_float(k, v.low, v.high,
                                                 log=v.log)
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(self._rng)
                else:
                    cfg[k] = v
            self._trials[tuple(sorted(cfg.items()))] = t
            return _unflatten(cfg)

        def tell(config, value):
            t = self._trials.pop(
                tuple(sorted(_flatten(config).items())), None)
            if t is not None:
                study.tell(t, value)

        super().__init__(ask, tell)


def bohb(param_space: dict, *, metric: str, mode: str = "max",
         num_samples: int = 16, max_t: int = 32, reduction_factor: int = 3,
         seed: int | None = None):
    """BOHB (Falkner et al. 2018) = HyperBand's budget allocation + a
    TPE-style KDE model proposing configs (reference:
    tune/schedulers/hb_bohb.py + tune/search/bohb/). Returns
    (searcher, scheduler) to pass to the Tuner."""
    from ray_tpu.tune.schedulers import HyperBandScheduler

    searcher = TPESearcher(param_space, metric=metric, mode=mode,
                           num_samples=num_samples, seed=seed)
    scheduler = HyperBandScheduler(metric=metric, mode=mode, max_t=max_t,
                                   reduction_factor=reduction_factor)
    return searcher, scheduler
