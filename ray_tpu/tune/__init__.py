from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ExternalSearcher,
    OptunaSearch,
    Searcher,
    TPESearcher,
    bohb,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "ASHAScheduler", "FIFOScheduler",
    "HyperBandScheduler", "PopulationBasedTraining", "MedianStoppingRule",
    "ResourceChangingScheduler", "Searcher", "BasicVariantGenerator",
    "TPESearcher", "uniform", "loguniform", "choice", "randint", "quniform",
    "grid_search", "PB2", "ExternalSearcher", "OptunaSearch", "bohb",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu('tune')
del _rlu
