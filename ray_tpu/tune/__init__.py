from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "ASHAScheduler", "FIFOScheduler",
    "PopulationBasedTraining", "MedianStoppingRule", "uniform", "loguniform",
    "choice", "randint", "quniform", "grid_search",
]
