"""Durable workflows: DAG execution with per-step checkpointing.

Parity: reference python/ray/workflow/ (workflow_executor.py,
workflow_storage.py) — each step's result is checkpointed to storage
before dependents run, so a crashed driver re-running the same workflow id
skips completed steps and resumes where it stopped.

Model: steps are memoized by (workflow_id, step name, occurrence index);
re-running the same program with the same workflow_id is resumption — the
reference's recovery path re-executes the DAG the same way, consulting the
step log.

Example::

    @workflow.step
    def add(a, b): return a + b

    out = workflow.run(add.step(add.step(1, 2), 3), workflow_id="w1",
                       storage="/tmp/wf")
"""

from __future__ import annotations

import hashlib
import os
import pickle
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")


class Step:
    def __init__(self, fn: Callable, args: tuple, kwargs: dict, name: str,
                 options: dict | None = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name
        self.options = options or {}


class StepFunction:
    def __init__(self, fn: Callable, options: dict | None = None):
        self._fn = fn
        self._options = options or {}
        self.name = getattr(fn, "__name__", "step")

    def step(self, *args, **kwargs) -> Step:
        return Step(self._fn, args, kwargs, self.name, self._options)

    def options(self, **opts) -> "StepFunction":
        return StepFunction(self._fn, {**self._options, **opts})

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(fn=None, **options):
    """@workflow.step decorator."""
    if fn is not None:
        return StepFunction(fn)
    return lambda f: StepFunction(f, options)


@dataclass
class _RunState:
    workflow_id: str
    storage: str
    counters: Counter = field(default_factory=Counter)

    def step_dir(self) -> str:
        d = os.path.join(self.storage, self.workflow_id, "steps")
        os.makedirs(d, exist_ok=True)
        return d

    def next_step_id(self, name: str) -> str:
        idx = self.counters[name]
        self.counters[name] += 1
        return f"{name}_{idx}"


def _result_path(state: _RunState, step_id: str) -> str:
    return os.path.join(state.step_dir(), f"{step_id}.pkl")


def _execute(node: Any, state: _RunState):
    if isinstance(node, Step):
        step_id = state.next_step_id(node.name)
        path = _result_path(state, step_id)
        # Resolve dependencies first (post-order), then memoize.
        args = tuple(_execute(a, state) for a in node.args)
        kwargs = {k: _execute(v, state) for k, v in node.kwargs.items()}
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        remote_fn = ray_tpu.remote(**node.options)(node.fn) \
            if node.options else ray_tpu.remote(node.fn)
        result = ray_tpu.get(remote_fn.remote(*args, **kwargs))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(result, f)
        os.replace(tmp, path)  # atomic commit (reference: workflow_storage)
        return result
    if isinstance(node, (list, tuple)):
        return type(node)(_execute(x, state) for x in node)
    return node


def run(dag: Step, *, workflow_id: str, storage: str | None = None):
    """Execute (or resume) a workflow; returns the final result."""
    state = _RunState(workflow_id, storage or _DEFAULT_STORAGE)
    result = _execute(dag, state)
    done_path = os.path.join(state.storage, workflow_id, "result.pkl")
    with open(done_path, "wb") as f:
        pickle.dump(result, f)
    return result


def get_output(workflow_id: str, *, storage: str | None = None):
    path = os.path.join(storage or _DEFAULT_STORAGE, workflow_id, "result.pkl")
    if not os.path.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no stored result")
    with open(path, "rb") as f:
        return pickle.load(f)


def list_workflows(*, storage: str | None = None) -> list[str]:
    root = storage or _DEFAULT_STORAGE
    if not os.path.isdir(root):
        return []
    return sorted(os.listdir(root))


def delete(workflow_id: str, *, storage: str | None = None) -> None:
    import shutil

    shutil.rmtree(os.path.join(storage or _DEFAULT_STORAGE, workflow_id),
                  ignore_errors=True)


__all__ = ["step", "run", "get_output", "list_workflows", "delete", "Step",
           "StepFunction"]
