"""Durable workflows: DAG execution with per-step checkpointing.

Parity: reference python/ray/workflow/ (workflow_executor.py,
workflow_storage.py) — each step's result is checkpointed to storage
before dependents run, so a crashed driver re-running the same workflow id
skips completed steps and resumes where it stopped.

Model: steps are memoized by (workflow_id, step name, occurrence index);
re-running the same program with the same workflow_id is resumption — the
reference's recovery path re-executes the DAG the same way, consulting the
step log.

Example::

    @workflow.step
    def add(a, b): return a + b

    out = workflow.run(add.step(add.step(1, 2), 3), workflow_id="w1",
                       storage="/tmp/wf")
"""

from __future__ import annotations

import hashlib
import os
import pickle
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")


class Step:
    def __init__(self, fn: Callable, args: tuple, kwargs: dict, name: str,
                 options: dict | None = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name
        self.options = options or {}


class StepFunction:
    def __init__(self, fn: Callable, options: dict | None = None):
        self._fn = fn
        self._options = options or {}
        self.name = getattr(fn, "__name__", "step")

    def step(self, *args, **kwargs) -> Step:
        return Step(self._fn, args, kwargs, self.name, self._options)

    def options(self, **opts) -> "StepFunction":
        return StepFunction(self._fn, {**self._options, **opts})

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(fn=None, **options):
    """@workflow.step decorator."""
    if fn is not None:
        return StepFunction(fn)
    return lambda f: StepFunction(f, options)


@dataclass
class _RunState:
    workflow_id: str
    storage: str
    counters: Counter = field(default_factory=Counter)
    # Continuation scope: sub-steps spawned by a step's returned Step get
    # ids under the parent's id ("f_0.g_0"), so a resume that
    # short-circuits the parent checkpoint (never re-entering the
    # continuation) cannot shift SIBLING step ids.
    prefix: list = field(default_factory=list)

    def step_dir(self) -> str:
        d = os.path.join(self.storage, self.workflow_id, "steps")
        os.makedirs(d, exist_ok=True)
        return d

    def next_step_id(self, name: str) -> str:
        scoped = ".".join(self.prefix + [name])
        idx = self.counters[scoped]
        self.counters[scoped] += 1
        return f"{scoped}_{idx}"


def _result_path(state: _RunState, step_id: str) -> str:
    return os.path.join(state.step_dir(), f"{step_id}.pkl")


def _execute(node: Any, state: _RunState, resolve_continuation: bool = True):
    if isinstance(node, Step):
        step_id = state.next_step_id(node.name)
        path = _result_path(state, step_id)
        # Resolve dependencies first (post-order), then memoize.
        args = tuple(_execute(a, state) for a in node.args)
        kwargs = {k: _execute(v, state) for k, v in node.kwargs.items()}
        if os.path.exists(path):
            with open(path, "rb") as f:
                return pickle.load(f)
        opts = dict(node.options)
        max_retries = opts.pop("max_retries", 0)
        catch = opts.pop("catch_exceptions", False)
        remote_fn = ray_tpu.remote(**opts)(node.fn) \
            if opts else ray_tpu.remote(node.fn)
        last_err: BaseException | None = None
        result = None
        for _ in range(max(1, max_retries + 1)):
            try:
                result = ray_tpu.get(remote_fn.remote(*args, **kwargs))
                last_err = None
                break
            except Exception as e:  # noqa: BLE001
                last_err = e
        # Dynamic workflow: a step returned another step (reference:
        # workflow.continuation). The OUTERMOST step of a chain resolves
        # it iteratively (long tail-chains must not hit the Python
        # recursion limit) under its own id scope, so a resume that
        # short-circuits this checkpoint cannot shift sibling step ids.
        # Continuation failures flow into the same last_err/catch
        # handling as the step's own failure.
        if resolve_continuation:
            while last_err is None and isinstance(result, Step):
                state.prefix.append(step_id)
                try:
                    result = _execute(result, state,
                                      resolve_continuation=False)
                except Exception as e:  # noqa: BLE001
                    last_err = e
                finally:
                    state.prefix.pop()
        if last_err is not None:
            if not catch:
                raise last_err
            # catch_exceptions: the step RESULT is (value, error) — the
            # error is durable too (reference: workflow step options).
            # Unwrap the task-error envelope to the application exception.
            cause = getattr(last_err, "cause", None)
            result = (None, cause if cause is not None else last_err)
        elif catch and not isinstance(result, Step):
            # A Step result is the NEXT continuation link, not a settled
            # value — wrapping it would halt the chain. A mid-chain step's
            # catch_exceptions covers its OWN execution; failures later in
            # the chain surface to the outermost step's handling.
            result = (result, None)
        if isinstance(result, Step):
            # Shallow (mid-chain) execution: the value is the NEXT
            # continuation, owned by the outermost step's loop — a Step is
            # not a durable value (its fn may not even pickle), so this
            # link re-executes on resume and only settled values persist.
            return result
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(result, f)
        os.replace(tmp, path)  # atomic commit (reference: workflow_storage)
        return result
    if isinstance(node, (list, tuple)):
        return type(node)(_execute(x, state) for x in node)
    return node


def _write_status(storage: str, workflow_id: str, status: str) -> None:
    d = os.path.join(storage, workflow_id)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "status"), "w") as f:
        f.write(status)


def get_status(workflow_id: str, *, storage: str | None = None) -> str:
    """RUNNING / SUCCEEDED / FAILED / NOT_FOUND (reference:
    workflow.get_status WorkflowStatus)."""
    path = os.path.join(storage or _DEFAULT_STORAGE, workflow_id, "status")
    if not os.path.exists(path):
        return "NOT_FOUND"
    with open(path) as f:
        return f.read().strip()


def run(dag: Step, *, workflow_id: str, storage: str | None = None):
    """Execute (or resume) a workflow; returns the final result."""
    state = _RunState(workflow_id, storage or _DEFAULT_STORAGE)
    _write_status(state.storage, workflow_id, "RUNNING")
    try:
        result = _execute(dag, state)
    except BaseException:
        _write_status(state.storage, workflow_id, "FAILED")
        raise
    done_path = os.path.join(state.storage, workflow_id, "result.pkl")
    with open(done_path, "wb") as f:
        pickle.dump(result, f)
    _write_status(state.storage, workflow_id, "SUCCEEDED")
    return result


def run_async(dag: Step, *, workflow_id: str, storage: str | None = None):
    """Run in a background thread; returns a concurrent.futures.Future
    (reference: workflow.run_async returns an ObjectRef)."""
    import concurrent.futures

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    fut = pool.submit(run, dag, workflow_id=workflow_id, storage=storage)
    pool.shutdown(wait=False)
    return fut


def get_output(workflow_id: str, *, storage: str | None = None):
    path = os.path.join(storage or _DEFAULT_STORAGE, workflow_id, "result.pkl")
    if not os.path.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no stored result")
    with open(path, "rb") as f:
        return pickle.load(f)


def list_workflows(*, storage: str | None = None) -> list[str]:
    root = storage or _DEFAULT_STORAGE
    if not os.path.isdir(root):
        return []
    return sorted(os.listdir(root))


def delete(workflow_id: str, *, storage: str | None = None) -> None:
    import shutil

    shutil.rmtree(os.path.join(storage or _DEFAULT_STORAGE, workflow_id),
                  ignore_errors=True)


class EventListener:
    """Subclass with poll_for_event(*args) blocking until the event fires
    and returning its payload (reference: workflow/event_listener.py)."""

    def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError


def wait_for_event(listener_cls: type, *args, **kwargs) -> Step:
    """A step that resolves when the external event arrives (reference:
    workflow.wait_for_event). The listener runs as a task; the payload
    checkpoints like any step result, so an already-received event is not
    re-awaited on resume."""

    def _wait(*a, **k):
        return listener_cls().poll_for_event(*a, **k)

    return Step(_wait, args, kwargs,
                name=f"event-{listener_cls.__name__}", options={})


def continuation(s: Step) -> Step:
    """Mark a step returned from inside a step as the workflow's
    continuation (reference: workflow.continuation). Returning the Step
    directly has the same effect; this exists for API parity."""
    return s


__all__ = ["step", "run", "run_async", "get_output", "get_status",
           "list_workflows", "delete", "Step", "StepFunction",
           "EventListener", "wait_for_event", "continuation"]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu('workflow')
del _rlu
