"""StandardAutoscaler: demand-driven cluster scaling.

Parity: reference python/ray/autoscaler/_private/autoscaler.py
(StandardAutoscaler.update :171/:373 reconciliation) +
resource_demand_scheduler.py:102 (bin-packing get_nodes_to_launch:170) +
load_metrics.py. Load comes from the GCS (pending lease demand reported in
raylet heartbeats + pending placement groups); the scheduler bin-packs
demand onto hypothetical nodes of the configured types and launches what's
missing; idle nodes beyond min_workers are terminated after idle_timeout.

TPU-first: a node type with hosts_per_slice > 1 is a pod slice — demand
for STRICT_ICI placement groups launches whole slices (the gang unit).
"""

from __future__ import annotations

import logging
import threading
import time

from ray_tpu._private.common import resources_fit, subtract_resources
from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, node_types: list[NodeType],
                 *, get_cluster_status, idle_timeout_s: float = 60.0,
                 upscaling_speed: float = 1.0, max_workers: int = 20,
                 drain_node=None, drain_deadline_s: float = 30.0):
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.get_cluster_status = get_cluster_status
        self.idle_timeout_s = idle_timeout_s
        self.upscaling_speed = upscaling_speed
        self.max_workers = max_workers
        # Called as drain_node(node_id, reason="idle", deadline_s=...)
        # before the provider tears the VM down (reference: drain
        # precedes termination so running leases finish and primary
        # object copies evacuate — DrainNode / HandleDrainRaylet analog).
        self.drain_node = drain_node
        self.drain_deadline_s = drain_deadline_s
        self._idle_since: dict[str, float] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- demand scheduling (reference: resource_demand_scheduler.py) ----

    def get_nodes_to_launch(self, pending_demand: list[dict],
                            pending_pgs: list[dict],
                            current_available: list[dict],
                            upcoming_by_type: dict[str, int] | None = None
                            ) -> dict[str, int]:
        """First-fit-decreasing bin-pack of unmet demand onto node types.
        upcoming_by_type: provider nodes still provisioning, per type —
        they absorb pending gang demand so a minutes-long TPU slice
        provision is not re-launched every tick."""
        bins = [dict(a) for a in current_available]
        to_launch: dict[str, int] = {}
        for demand in sorted(pending_demand,
                             key=lambda d: -sum(d.values())):
            placed = False
            for b in bins:  # existing nodes AND already-planned launches
                if resources_fit(b, demand):
                    subtract_resources(b, demand)
                    placed = True
                    break
            if placed:
                continue
            for t in self.node_types.values():
                if resources_fit(t.resources, demand):
                    to_launch[t.name] = to_launch.get(t.name, 0) + 1
                    bins.append(dict(t.resources))
                    subtract_resources(bins[-1], demand)
                    break
            else:
                logger.warning("demand %s fits no node type", demand)
        # STRICT_ICI placement groups: launch whole slices.
        upcoming = dict(upcoming_by_type or {})
        for pg in pending_pgs:
            if pg.get("strategy") != "STRICT_ICI":
                continue
            bundles = pg["bundles"]
            for t in self.node_types.values():
                if t.hosts_per_slice > 1 and all(
                        resources_fit(t.resources, b) for b in bundles):
                    # A slice of this type still provisioning absorbs
                    # this gang: launching another every reconcile tick
                    # of a minutes-long provision would duplicate TPU
                    # slices. Each provisioning slice absorbs ONE gang.
                    if upcoming.get(t.name, 0) > 0:
                        upcoming[t.name] -= 1
                    else:
                        to_launch[t.name] = to_launch.get(t.name, 0) + 1
                    break
        return to_launch

    # ---- reconcile loop (reference: StandardAutoscaler.update) ----

    def update(self) -> dict:
        status = self.get_cluster_status()
        alive = [n for n in status["nodes"] if n["alive"]]
        available = [n["available_resources"] for n in alive]
        demand = status.get("pending_demand", [])
        pgs = status.get("pending_placement_groups", [])

        current = self.provider.non_terminated_nodes()
        # Provider nodes with no GCS registration yet (queued/provisioning
        # cloud capacity) still satisfy demand ONCE UP: count their full
        # resources as upcoming bins, or every tick of a minutes-long
        # TPU provision would launch a duplicate slice (reference:
        # resource_demand_scheduler counts launching nodes as upcoming).
        registered = {n["node_id"] for n in alive}
        for key in ("tpu-slice", "node-name"):
            registered |= {(n.get("labels") or {}).get(key) for n in alive}
        upcoming = []
        upcoming_by_type: dict[str, int] = {}
        for nid in current:
            if nid in registered:
                continue
            t_name = self.provider.node_type(nid)
            t = self.node_types.get(t_name)
            if t is not None:
                upcoming.append(dict(t.resources))
                upcoming_by_type[t_name] = upcoming_by_type.get(t_name, 0) + 1
        launched: dict[str, int] = {}
        if len(current) < self.max_workers:
            to_launch = self.get_nodes_to_launch(demand, pgs,
                                                 available + upcoming,
                                                 upcoming_by_type)
            count_by_type: dict[str, int] = {}
            for nid in current:
                tn = self.provider.node_type(nid)
                count_by_type[tn] = count_by_type.get(tn, 0) + 1
            total = len(current)
            for type_name, count in to_launch.items():
                t = self.node_types[type_name]
                # Rate limit (reference: upscaling_speed — grow by at most
                # speed × current-of-type per tick, min 1) and per-type +
                # global max_workers caps; `total` tracks THIS tick's
                # launches so multiple types cannot jointly exceed the cap.
                have = count_by_type.get(type_name, 0)
                rate_cap = max(1, int(self.upscaling_speed * max(1, have)))
                count = min(count, rate_cap, t.max_workers - have,
                            self.max_workers - total)
                if count > 0:
                    logger.info("autoscaler launching %d x %s", count, type_name)
                    self.provider.create_node(t, count)
                    launched[type_name] = count
                    total += count
                    count_by_type[type_name] = have + count

        # Idle termination: fully-available worker nodes past the timeout.
        # A provider node maps to GCS nodes either directly by id (fake
        # provider) or through the `tpu-slice` label (cloud slices: one
        # provider node = a whole multi-host slice registering under its
        # own GCS node ids) — a slice is idle only when EVERY host is.
        terminated = []
        to_terminate: list[tuple[str, list[dict]]] = []
        now = time.monotonic()
        by_id = {n["node_id"]: n for n in alive}
        by_slice: dict[str, list[dict]] = {}
        for n in alive:
            labels = n.get("labels") or {}
            # `tpu-slice` (GCP multi-host slices) or `node-name` (AWS
            # instances) — either maps GCS nodes to the provider node.
            for key in ("tpu-slice", "node-name"):
                if labels.get(key):
                    by_slice.setdefault(labels[key], []).append(n)
                    break
        min_by_type: dict[str, int] = {}
        for nid in list(current):
            infos = [by_id[nid]] if nid in by_id else by_slice.get(nid, [])
            if not infos:
                continue
            t_name = self.provider.node_type(nid)
            t = self.node_types.get(t_name)
            idle = not demand and all(
                i["available_resources"] == i["total_resources"]
                for i in infos)
            if not idle:
                self._idle_since.pop(nid, None)
                continue
            first_idle = self._idle_since.setdefault(nid, now)
            kept = min_by_type.get(t_name, 0)
            if now - first_idle > self.idle_timeout_s and t is not None \
                    and kept >= t.min_workers:
                logger.info("autoscaler terminating idle node %s", nid[:8])
                to_terminate.append((nid, infos))
            else:
                min_by_type[t_name] = kept + 1
        if to_terminate:
            if self.drain_node is not None:
                # Drain the whole batch CONCURRENTLY: each drain waits
                # for DRAINED (up to its deadline), and serializing N of
                # them would stall this single update thread — and with
                # it upscale decisions — for N x deadline.
                from concurrent.futures import ThreadPoolExecutor

                drain_infos = [i for _nid, infos in to_terminate
                               for i in infos]
                with ThreadPoolExecutor(
                        max_workers=min(8, len(drain_infos))) as pool:
                    list(pool.map(
                        lambda i: self.drain_node(
                            i["node_id"], reason="idle",
                            deadline_s=self.drain_deadline_s),
                        drain_infos))
            for nid, _infos in to_terminate:
                self.provider.terminate_node(nid)
                terminated.append(nid)
                self._idle_since.pop(nid, None)
        return {"launched": launched, "terminated": terminated,
                "demand": len(demand)}

    def start(self, interval_s: float = 1.0):
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.update()
                except Exception:
                    logger.exception("autoscaler update failed")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
