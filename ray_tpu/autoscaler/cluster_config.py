"""Cluster YAML config: schema + provider construction.

Parity: reference python/ray/autoscaler/ray-schema.json + `ray up`
(autoscaler/_private/commands.py).  Shape::

    cluster_name: my-tpu-cluster
    max_workers: 16
    idle_timeout_minutes: 5
    provider:
      type: gcp_tpu            # or: fake
      project: my-project
      zone: us-central2-b
      accelerator_type: v5e-8
      runtime_version: tpu-ubuntu2204-base
    available_node_types:
      tpu_worker:
        resources: {"TPU": 8, "CPU": 16}
        min_workers: 0
        max_workers: 8
        hosts_per_slice: 1
"""

from __future__ import annotations

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

_REQUIRED = ("cluster_name", "provider", "available_node_types")


def load_cluster_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    validate_cluster_config(cfg)
    return cfg


def validate_cluster_config(cfg: dict) -> None:
    for key in _REQUIRED:
        if key not in cfg:
            raise ValueError(f"cluster config missing {key!r}")
    if "type" not in cfg["provider"]:
        raise ValueError("provider config needs 'type'")
    for name, t in cfg["available_node_types"].items():
        if "resources" not in t:
            raise ValueError(f"node type {name!r} needs 'resources'")


def node_types_from_config(cfg: dict) -> list[NodeType]:
    out = []
    for name, t in cfg["available_node_types"].items():
        out.append(NodeType(
            name=name,
            resources=dict(t["resources"]),
            labels=dict(t.get("labels", {})),
            min_workers=int(t.get("min_workers", 0)),
            max_workers=int(t.get("max_workers", cfg.get("max_workers", 10))),
            hosts_per_slice=int(t.get("hosts_per_slice", 1))))
    return out


def make_provider(cfg: dict, runtime_node=None) -> NodeProvider:
    ptype = cfg["provider"]["type"]
    if ptype == "fake":
        from ray_tpu.autoscaler.node_provider import FakeNodeProvider

        if runtime_node is None:
            raise ValueError("fake provider needs the local RuntimeNode")
        return FakeNodeProvider(runtime_node, cfg["provider"])
    if ptype == "gcp_tpu":
        from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider

        return GCPTPUNodeProvider(cfg["provider"])
    if ptype == "aws_ec2":
        from ray_tpu.autoscaler.aws_ec2 import AWSEC2NodeProvider

        # The YAML's top-level cluster_name IS the tag-isolation key;
        # without it every cluster would filter as "default" and count
        # sibling clusters' instances as its own capacity.
        pcfg = dict(cfg["provider"])
        pcfg.setdefault("cluster_name", cfg["cluster_name"])
        return AWSEC2NodeProvider(pcfg)
    raise ValueError(f"unknown provider type {ptype!r}")
