"""Node provider plugin interface + fake provider.

Parity: reference python/ray/autoscaler/node_provider.py (plugin API) and
autoscaler/_private/fake_multi_node/ (the fake provider that backs
hermetic autoscaler tests). The GCP TPU-VM provider pattern (reference:
autoscaler/gcp/node_provider.py:77-90 GCPTPU + tpu_command_runner.py:56
fan-out to all hosts of a TPU-VM slice) shapes the API: `create_node`
takes a *node type* whose config may declare a whole ICI slice, and the
provider is expected to bring up every host of the slice as one gang.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any


@dataclass
class NodeType:
    """One entry of available_node_types (reference: cluster YAML schema)."""

    name: str
    resources: dict
    labels: dict = field(default_factory=dict)
    min_workers: int = 0
    max_workers: int = 10
    # TPU slices: hosts per gang (a v4-32 slice = 4 hosts that must be
    # created/terminated together).
    hosts_per_slice: int = 1


def cli_run(binary: str, cmd: list[str], timeout: float = 600) -> str:
    """Shared cloud-CLI runner for shell-out providers (gcloud, aws):
    which-lookup, bounded run, stderr-tail error. cmd[0] is replaced
    with the resolved binary path."""
    import shutil
    import subprocess

    path = shutil.which(binary)
    if path is None:
        raise RuntimeError(
            f"{binary} CLI not found; this provider requires it on the "
            "head node")
    cmd = [path] + cmd[1:]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(f"{' '.join(cmd)} failed: {out.stderr[-500:]}")
    return out.stdout


class NodeProvider:
    """Subclass per cloud. All methods are called from the autoscaler loop."""

    def __init__(self, config: dict):
        self.config = config

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_resources(self, node_id: str) -> dict:
        raise NotImplementedError

    def node_type(self, node_id: str) -> str:
        raise NotImplementedError

    def create_node(self, node_type: NodeType, count: int = 1) -> list[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Spawns real raylet processes in the local session — full multi-node
    semantics without a cloud (reference: fake_multi_node provider)."""

    def __init__(self, runtime_node, config: dict | None = None):
        super().__init__(config or {})
        self._runtime = runtime_node  # ray_tpu._private.node.RuntimeNode
        self._nodes: dict[str, dict] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            dead = [nid for nid, info in self._nodes.items()
                    if info["handle"].proc.poll() is not None]
            for nid in dead:
                del self._nodes[nid]
            return list(self._nodes)

    def node_resources(self, node_id: str) -> dict:
        return self._nodes[node_id]["type"].resources

    def node_type(self, node_id: str) -> str:
        return self._nodes[node_id]["type"].name

    def create_node(self, node_type: NodeType, count: int = 1) -> list[str]:
        created = []
        for _ in range(count):
            slice_id = uuid.uuid4().hex[:8]
            for host in range(node_type.hosts_per_slice):
                labels = dict(node_type.labels)
                if node_type.hosts_per_slice > 1:
                    labels["tpu-slice"] = f"{node_type.name}-{slice_id}"
                    labels["tpu-worker-id"] = str(host)
                handle = self._runtime.start_raylet(
                    resources=dict(node_type.resources), labels=labels)
                with self._lock:
                    self._nodes[handle.node_id] = {
                        "handle": handle, "type": node_type}
                created.append(handle.node_id)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info:
            info["handle"].kill()
