"""GCP TPU-VM node provider.

Parity: reference python/ray/autoscaler/gcp/node_provider.py:77-90 (GCPTPU
resource class) + gcp/tpu_command_runner.py:56 (TPUCommandRunner fans
setup/start commands out to every host of a TPU-VM slice) + gcp/config.py.

Re-design notes: the reference drives the GCE REST API through
googleapiclient; this provider shells out to `gcloud` (the TPU-VM
queued-resources flow), which is what the TPU provisioning docs
standardize on and keeps the provider dependency-free.  One *node* here
is one TPU-VM (possibly multi-host) slice — the ICI gang unit — matching
the STRICT_ICI scheduling model (SURVEY.md §7 stage 3: slices live and
die together).
"""

from __future__ import annotations

import json
import logging
import uuid

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)


class GCPTPUNodeProvider(NodeProvider):
    """Provisions TPU-VM slices via `gcloud compute tpus`.

    config keys: project, zone, accelerator_type (e.g. "v5e-8"),
    runtime_version, optional reserved/spot, optional use_queued_resources.
    """

    def __init__(self, config: dict):
        super().__init__(config)
        for key in ("project", "zone", "accelerator_type", "runtime_version"):
            if key not in config:
                raise ValueError(f"GCPTPUNodeProvider config needs {key!r}")
        self._nodes: dict[str, dict] = {}

    # -- gcloud plumbing (separated so tests can assert the exact argv) --

    def create_command(self, name: str, node_type: NodeType) -> list[str]:
        cfg = self.config
        if cfg.get("use_queued_resources", True):
            # Queued resources: the supported path for v5e/v5p/v6e slices
            # and for spot/reserved capacity.
            cmd = [
                "gcloud", "compute", "tpus", "queued-resources", "create",
                name,
                f"--node-id={name}",
                f"--project={cfg['project']}",
                f"--zone={cfg['zone']}",
                f"--accelerator-type={cfg['accelerator_type']}",
                f"--runtime-version={cfg['runtime_version']}",
            ]
            if cfg.get("spot"):
                cmd.append("--spot")
            if cfg.get("reserved"):
                cmd.append("--reserved")
        else:
            cmd = [
                "gcloud", "compute", "tpus", "tpu-vm", "create", name,
                f"--project={cfg['project']}",
                f"--zone={cfg['zone']}",
                f"--accelerator-type={cfg['accelerator_type']}",
                f"--version={cfg['runtime_version']}",
            ]
        return cmd

    def delete_command(self, name: str) -> list[str]:
        cfg = self.config
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "delete", name,
            f"--project={cfg['project']}", f"--zone={cfg['zone']}",
            "--quiet",
        ]

    def ssh_fanout_command(self, name: str, remote_cmd: str) -> list[str]:
        """Run `remote_cmd` on EVERY host of the slice (reference:
        tpu_command_runner.py:56 TPUCommandRunner --worker=all fan-out)."""
        cfg = self.config
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
            f"--project={cfg['project']}", f"--zone={cfg['zone']}",
            "--worker=all", f"--command={remote_cmd}",
        ]

    def list_command(self) -> list[str]:
        cfg = self.config
        return [
            "gcloud", "compute", "tpus", "tpu-vm", "list",
            f"--project={cfg['project']}", f"--zone={cfg['zone']}",
            "--format=json",
        ]

    def list_queued_command(self) -> list[str]:
        cfg = self.config
        return [
            "gcloud", "compute", "tpus", "queued-resources", "list",
            f"--project={cfg['project']}", f"--zone={cfg['zone']}",
            "--format=json",
        ]

    def delete_queued_command(self, name: str) -> list[str]:
        cfg = self.config
        return [
            "gcloud", "compute", "tpus", "queued-resources", "delete", name,
            f"--project={cfg['project']}", f"--zone={cfg['zone']}",
            "--quiet", "--force",
        ]

    def _run(self, cmd: list[str]) -> str:
        from ray_tpu.autoscaler.node_provider import cli_run

        return cli_run("gcloud", cmd)

    # -- NodeProvider interface --

    NAME_PREFIX = "ray-tpu-"

    def non_terminated_nodes(self) -> list[str]:
        """Nodes this CLUSTER owns (name-prefix filter — a shared zone may
        hold unrelated TPUs): READY/CREATING tpu-vms plus queued resources
        still waiting for capacity (so pending gangs are not double-
        launched every autoscaler tick).  READY nodes that were created
        via the async queued-resources flow get their deferred raylet
        bootstrap here (create-time SSH would race provisioning)."""
        names = []
        try:
            listed = json.loads(self._run(self.list_command()) or "[]")
        except RuntimeError:
            listed = None
        if listed is None:
            return list(self._nodes)
        for tpu in listed:
            name = tpu.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(self.NAME_PREFIX):
                continue
            state = tpu.get("state", "")
            if state in ("READY", "CREATING"):
                names.append(name)
                info = self._nodes.setdefault(
                    name, {"type_name": "tpu", "queued": True})
                if state == "READY" and not info.get("bootstrapped"):
                    self._bootstrap(name, info)
        # Queued resources not yet materialized as tpu-vms still count as
        # pending capacity.
        try:
            queued = json.loads(self._run(self.list_queued_command()) or "[]")
        except RuntimeError:
            queued = []
        for qr in queued:
            name = qr.get("name", "").rsplit("/", 1)[-1]
            state = qr.get("state", {})
            if isinstance(state, dict):
                state = state.get("state", "")
            if name.startswith(self.NAME_PREFIX) and name not in names \
                    and state in ("WAITING_FOR_RESOURCES", "PROVISIONING",
                                  "ACCEPTED"):
                names.append(name)
                self._nodes.setdefault(name, {"type_name": "tpu",
                                              "queued": True})
        return names

    def _bootstrap(self, name: str, info: dict) -> None:
        """Start the raylet on every host of a now-READY slice."""
        head = self.config.get("head_address")
        if not head:
            info["bootstrapped"] = True
            return
        # TPU_NAME ties every host's raylet to this provider node: the
        # autoscaler matches GCS nodes back to the slice through the
        # resulting `tpu-slice` label for idle-drain-terminate.
        start = (f"TPU_NAME={name} "
                 f"python -m ray_tpu.scripts start --address={head}")
        try:
            self._run(self.ssh_fanout_command(name, start))
            info["bootstrapped"] = True
            info.pop("bootstrap_error", None)
        except RuntimeError as e:
            # Surfaced, counted, retried next tick — a slice that never
            # bootstraps must be visible, not silently half-provisioned.
            info["bootstrap_failures"] = info.get("bootstrap_failures", 0) + 1
            info["bootstrap_error"] = str(e)
            logger.warning("bootstrap of slice %s failed (attempt %d): %s",
                           name, info["bootstrap_failures"], e)

    def node_resources(self, node_id: str) -> dict:
        chips = int(self.config["accelerator_type"].rsplit("-", 1)[-1])
        return {"TPU": float(chips)}

    def node_type(self, node_id: str) -> str:
        return self._nodes.get(node_id, {}).get("type_name", "tpu")

    def create_node(self, node_type: NodeType, count: int = 1) -> list[str]:
        created = []
        use_qr = self.config.get("use_queued_resources", True)
        for _ in range(count):
            name = f"{self.NAME_PREFIX}{node_type.name}-{uuid.uuid4().hex[:8]}"
            self._run(self.create_command(name, node_type))
            self._nodes[name] = {"type_name": node_type.name,
                                 "queued": use_qr}
            # Raylet bootstrap is deferred to non_terminated_nodes once the
            # slice reports READY (queued-resources creation is async and
            # can take minutes to hours).
            created.append(name)
        return created

    def terminate_node(self, node_id: str) -> None:
        info = self._nodes.get(node_id, {})
        try:
            if info.get("queued", True):
                # Queued-resource-managed slices must be deleted through
                # the queued-resources API (tpu-vm delete is rejected).
                self._run(self.delete_queued_command(node_id))
            else:
                self._run(self.delete_command(node_id))
        finally:
            self._nodes.pop(node_id, None)
