from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    NodeProvider,
    NodeType,
)

__all__ = ["StandardAutoscaler", "NodeProvider", "FakeNodeProvider", "NodeType"]
