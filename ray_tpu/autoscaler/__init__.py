from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.cluster_config import (
    load_cluster_config,
    make_provider,
    node_types_from_config,
    validate_cluster_config,
)
from ray_tpu.autoscaler.aws_ec2 import AWSEC2NodeProvider
from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    NodeProvider,
    NodeType,
)

__all__ = ["StandardAutoscaler", "NodeProvider", "FakeNodeProvider",
           "NodeType", "GCPTPUNodeProvider", "AWSEC2NodeProvider",
           "load_cluster_config",
           "validate_cluster_config", "node_types_from_config",
           "make_provider"]
