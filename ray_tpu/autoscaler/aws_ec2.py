"""AWS EC2 node provider.

Parity: reference python/ray/autoscaler/_private/aws/node_provider.py
(AWSNodeProvider over boto3: run_instances/terminate_instances with
ray-cluster-name tag filtering, config.py:1 bootstrap_aws) — the second
cloud beside GCP, making the autoscaler genuinely multi-cloud.

Re-design notes: same choice as the GCP provider (gcp_tpu.py) — shell
out to the `aws` CLI instead of importing boto3, keeping the provider
dependency-free. Cluster membership rides a Name-tag prefix (the
reference tags instances with ray-cluster-name and filters on it);
raylet bootstrap rides EC2 user-data at launch (the reference's
equivalent of its ssh command runner setup, without needing inbound
SSH), so a node joins the cluster the moment cloud-init runs.
"""

from __future__ import annotations

import json
import logging
import uuid

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)


class AWSEC2NodeProvider(NodeProvider):
    """Provisions EC2 instances via the `aws` CLI.

    config keys: region, instance_type, ami, optional subnet_id,
    security_group_ids, key_name, iam_instance_profile, spot,
    head_address (raylet bootstrap target), cluster_name.
    """

    NAME_PREFIX = "ray-tpu-"

    def __init__(self, config: dict):
        super().__init__(config)
        # head_address is required: without it user-data would run
        # `start --address=` (rejected by scripts.py) and the instance
        # would sit forever as phantom "upcoming" capacity absorbing
        # demand the cluster never serves.
        for key in ("region", "instance_type", "ami", "head_address"):
            if key not in config:
                raise ValueError(f"AWSEC2NodeProvider config needs {key!r}")
        self.cluster_name = config.get("cluster_name", "default")
        self._nodes: dict[str, dict] = {}

    # -- aws CLI plumbing (separated so tests can assert the exact argv) --

    def _user_data(self, name: str) -> str:
        """Cloud-init script: starts a raylet pointed at the head on
        first boot, labeled so the autoscaler can match the GCS node
        back to this instance for idle-drain-terminate (the GCP
        provider's TPU_NAME contract, here RAY_TPU_NODE_NAME). Passed
        RAW: `aws ec2 run-instances --user-data` base64-encodes it
        itself — pre-encoding would hand cloud-init double-encoded
        garbage and the raylet would never start."""
        head = self.config.get("head_address", "")
        return ("#!/bin/bash\n"
                f"RAY_TPU_NODE_NAME={name} "
                f"python3 -m ray_tpu.scripts start --address={head}\n")

    def create_command(self, name: str, node_type: NodeType) -> list[str]:
        cfg = self.config
        tags = (f"ResourceType=instance,Tags=["
                f"{{Key=Name,Value={name}}},"
                f"{{Key=ray-cluster-name,Value={self.cluster_name}}}]")
        cmd = [
            "aws", "ec2", "run-instances",
            f"--region={cfg['region']}",
            f"--image-id={cfg['ami']}",
            f"--instance-type={cfg['instance_type']}",
            "--count=1",
            f"--tag-specifications={tags}",
            f"--user-data={self._user_data(name)}",
            "--output=json",
        ]
        if cfg.get("subnet_id"):
            cmd.append(f"--subnet-id={cfg['subnet_id']}")
        if cfg.get("security_group_ids"):
            # Separate argv tokens: a space-joined value would reach the
            # API as ONE malformed group id.
            cmd.append("--security-group-ids")
            cmd.extend(cfg["security_group_ids"])
        if cfg.get("key_name"):
            cmd.append(f"--key-name={cfg['key_name']}")
        if cfg.get("iam_instance_profile"):
            cmd.append(
                f"--iam-instance-profile=Name={cfg['iam_instance_profile']}")
        if cfg.get("spot"):
            cmd.append("--instance-market-options=MarketType=spot")
        return cmd

    def list_command(self) -> list[str]:
        cfg = self.config
        return [
            "aws", "ec2", "describe-instances",
            f"--region={cfg['region']}",
            "--filters",
            f"Name=tag:ray-cluster-name,Values={self.cluster_name}",
            "Name=instance-state-name,Values=pending,running",
            "--output=json",
        ]

    def terminate_command(self, instance_id: str) -> list[str]:
        cfg = self.config
        return [
            "aws", "ec2", "terminate-instances",
            f"--region={cfg['region']}",
            f"--instance-ids={instance_id}",
            "--output=json",
        ]

    def _run(self, cmd: list[str]) -> str:
        from ray_tpu.autoscaler.node_provider import cli_run

        return cli_run("aws", cmd)

    def _type_from_name(self, name: str) -> str:
        """Recover the node-type from the Name tag (f"{PREFIX}{type}-
        {hex8}") — after a head restart _nodes is empty, and a wrong
        type would exclude the node from upcoming-capacity counting AND
        idle termination (it would run and bill forever)."""
        body = name[len(self.NAME_PREFIX):]
        return body.rsplit("-", 1)[0] if "-" in body else body

    # -- NodeProvider interface --

    def non_terminated_nodes(self) -> list[str]:
        """Pending/running instances of THIS cluster (tag filter). Keyed
        by the Name tag (stable across the instance lifecycle and what
        the GCS node label carries); instance ids live in _nodes. The
        result is the UNION of described and locally-known nodes:
        describe-instances is eventually consistent, and a just-launched
        instance missing from one listing must not trigger a duplicate
        launch."""
        try:
            listed = json.loads(self._run(self.list_command()) or "{}")
        except RuntimeError:
            return list(self._nodes)
        names = []
        for res in listed.get("Reservations", []):
            for inst in res.get("Instances", []):
                tags = {t["Key"]: t["Value"] for t in inst.get("Tags", [])}
                name = tags.get("Name", "")
                if not name.startswith(self.NAME_PREFIX):
                    continue
                names.append(name)
                self._nodes.setdefault(
                    name, {"type_name": self._type_from_name(name)})[
                    "instance_id"] = inst.get("InstanceId")
        # Locally-known nodes missing from the listing stay for a few
        # ticks (consistency window) but are evicted after 3 consecutive
        # misses — a spot reclaim or external terminate must not leave
        # phantom capacity absorbing demand forever.
        for name in list(self._nodes):
            if name in names:
                self._nodes[name].pop("misses", None)
                continue
            misses = self._nodes[name].get("misses", 0) + 1
            if misses >= 3:
                self._nodes.pop(name)
            else:
                self._nodes[name]["misses"] = misses
                names.append(name)
        return names

    def node_resources(self, node_id: str) -> dict:
        return dict(self.config.get("resources", {"CPU": 1.0}))

    def node_type(self, node_id: str) -> str:
        return self._nodes.get(node_id, {}).get("type_name", "worker")

    def create_node(self, node_type: NodeType, count: int = 1) -> list[str]:
        created = []
        for _ in range(count):
            name = f"{self.NAME_PREFIX}{node_type.name}-{uuid.uuid4().hex[:8]}"
            out = json.loads(self._run(self.create_command(name, node_type))
                             or "{}")
            iid = None
            for inst in out.get("Instances", []):
                iid = inst.get("InstanceId")
            self._nodes[name] = {"type_name": node_type.name,
                                 "instance_id": iid}
            created.append(name)
        return created

    def terminate_node(self, node_id: str) -> None:
        info = self._nodes.get(node_id, {})
        iid = info.get("instance_id")
        try:
            if iid:
                self._run(self.terminate_command(iid))
        finally:
            self._nodes.pop(node_id, None)
