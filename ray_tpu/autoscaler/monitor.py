"""Autoscaler monitor: the standalone process that scales a live cluster.

Parity: reference python/ray/autoscaler/_private/monitor.py — a process
beside the GCS that reads load (pending lease demand + pending placement
groups) from the control plane, runs `StandardAutoscaler.update()` on an
interval, and drains nodes through the GCS before terminating them
(reference: autoscaler.py:171 update reconciliation; drain via
DrainNode, the analog of node_manager.cc HandleDrainRaylet).

Run::

    python -m ray_tpu.autoscaler.monitor \
        --address 127.0.0.1:6379 --config cluster.yaml

The monitor owns no cluster state: everything it needs is re-read from
the GCS each tick, so it can crash and restart freely (same stateless
design as the reference's monitor).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import threading

from ray_tpu._private import rpc
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.cluster_config import (
    load_cluster_config, make_provider, node_types_from_config)

logger = logging.getLogger(__name__)


class Monitor:
    """GCS-backed status/drain plumbing + the autoscaler loop."""

    def __init__(self, gcs_host: str, gcs_port: int, provider, node_types,
                 *, idle_timeout_s: float = 300.0,
                 upscaling_speed: float = 1.0, max_workers: int = 20):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        daemon=True, name="monitor-rpc")
        self._thread.start()
        # Resilient session: the monitor polls across GCS restarts and
        # network flaps without rebuilding its loop thread.
        self._conn = self._call_async(rpc.connect_session(
            gcs_host, gcs_port, name="monitor->gcs",
            grace_s=60.0, connect_timeout_s=30.0))
        self.autoscaler = StandardAutoscaler(
            provider, node_types,
            get_cluster_status=self.get_cluster_status,
            drain_node=self.drain_node,
            idle_timeout_s=idle_timeout_s,
            upscaling_speed=upscaling_speed, max_workers=max_workers)

    def _call_async(self, coro, timeout: float = 30.0):
        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(
            timeout)

    def get_cluster_status(self) -> dict:
        return self._call_async(self._conn.call("GetClusterStatus", {}))

    def drain_node(self, node_id: str, reason: str = "idle",
                   deadline_s: float = 30.0) -> bool:
        """Graceful drain before the provider tears the VM down: the
        raylet evacuates leases, objects, and pinned HBM while the GCS
        migrates actors. Waits (bounded) for DRAINED so termination
        never races the evacuation. The GCS now PROPAGATES drain
        failures — retry once, then escalate in the log and let the
        caller terminate an undrained node knowingly."""
        resp = {}
        for attempt in (1, 2):
            try:
                resp = self._call_async(self._conn.call(
                    "DrainNode", {"node_id": node_id, "reason": reason,
                                  "deadline_s": deadline_s}))
            except Exception as e:
                resp = {"ok": False, "error": str(e)}
            if resp.get("ok"):
                break
            logger.warning("drain of node %s failed (attempt %d): %s",
                           node_id[:8], attempt, resp.get("error"))
        if not resp.get("ok"):
            logger.error("node %s could not be drained (%s); terminating "
                         "UNDRAINED — running work will be recovered the "
                         "expensive way", node_id[:8], resp.get("error"))
            self._notify_node_dead(node_id, "terminated undrained by "
                                            "autoscaler (drain failed)")
            return False
        from ray_tpu._private.common import wait_for_drained

        outcome, me = wait_for_drained(
            lambda: self._call_async(
                self._conn.call("GetAllNodes", {}))["nodes"],
            node_id, deadline_s)
        if outcome == "DRAINED":
            return True
        if outcome in ("DIED", "GONE"):
            # Dead mid-drain WITHOUT reaching DRAINED: the evacuation
            # never finished — running work on it is being recovered
            # the expensive way. That is a drain failure, not success.
            logger.error("node %s died mid-drain (state=%s) before "
                         "DRAINED", node_id[:8],
                         me.get("state") if me else "gone")
            return False
        logger.warning("node %s did not reach DRAINED within its "
                       "deadline (%s); terminating anyway", node_id[:8],
                       outcome)
        self._notify_node_dead(node_id, "terminated mid-drain by "
                                        "autoscaler (deadline expired)")
        return False

    def _notify_node_dead(self, node_id: str, reason: str) -> None:
        """Hand the GCS a death certificate for a node the provider is
        about to terminate undrained. Without it the GCS only notices
        via heartbeat grace (tens of seconds) — actors and lineage on
        the node sit unrecovered the whole time. Best-effort: if the
        notify fails, the heartbeat path still converges."""
        try:
            self._call_async(self._conn.call(
                "NotifyNodeDead", {"node_id": node_id, "reason": reason}))
        except Exception as e:
            logger.warning("NotifyNodeDead for %s failed (%s); GCS will "
                           "fall back to heartbeat expiry", node_id[:8], e)

    def run(self, interval_s: float = 5.0):
        self.autoscaler.start(interval_s=interval_s)

    def run_blocking(self, interval_s: float = 5.0):
        import time

        self.run(interval_s)
        try:
            while True:
                time.sleep(60)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self):
        self.autoscaler.stop()
        try:
            self._call_async(self._conn.close(), timeout=5.0)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5.0)


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="[monitor] %(asctime)s %(message)s")
    ap = argparse.ArgumentParser()
    ap.add_argument("--address", required=True, help="GCS host:port")
    ap.add_argument("--config", required=True, help="cluster YAML")
    ap.add_argument("--interval", type=float, default=5.0)
    args = ap.parse_args(argv)
    cfg = load_cluster_config(args.config)
    host, port = args.address.rsplit(":", 1)
    provider = make_provider(cfg)
    monitor = Monitor(
        host, int(port), provider, node_types_from_config(cfg),
        idle_timeout_s=60.0 * float(cfg.get("idle_timeout_minutes", 5)),
        upscaling_speed=float(cfg.get("upscaling_speed", 1.0)),
        max_workers=int(cfg.get("max_workers", 20)))
    monitor.run_blocking(args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
