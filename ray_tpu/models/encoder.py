"""Bidirectional encoder (BERT-family) and encoder-decoder (T5-family),
TPU-first.

The reference ships no model implementations (fine-tunes run through
external torch engines — reference: release/release_tests.yaml ML gates);
here the encoder families round out the model zoo next to the Llama
decoder, MoE, ViT, and DiT. Same conventions as models/llama.py:
flax.linen, (batch, seq, d_model) activations, bf16-friendly params,
f32 norms, parameter names aligned with ray_tpu.parallel rules so
TP/FSDP shardings apply by rule.

Masked-LM objective for the encoder; prefix-LM / seq2seq cross-entropy
for the encoder-decoder.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# Shared blocks
# ---------------------------------------------------------------------------


class _Attention(nn.Module):
    """Full (bidirectional or causal or cross) attention. Encoder work is
    large dense batched matmuls — exactly MXU shape; masking is additive
    so XLA fuses it into the softmax."""

    d_model: int
    n_heads: int
    dtype: Any
    causal: bool = False

    @nn.compact
    def __call__(self, x, kv=None, mask=None):
        # x: (B, S, D) queries; kv: keys/values source (defaults to x).
        kv = x if kv is None else kv
        B, Sq, _ = x.shape
        Sk = kv.shape[1]
        H = self.n_heads
        Dh = self.d_model // H
        dense = lambda n, name: nn.Dense(n, use_bias=False, dtype=self.dtype,
                                         param_dtype=self.dtype, name=name)
        q = dense(H * Dh, "q_proj")(x).reshape(B, Sq, H, Dh)
        k = dense(H * Dh, "k_proj")(kv).reshape(B, Sk, H, Dh)
        v = dense(H * Dh, "v_proj")(kv).reshape(B, Sk, H, Dh)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / jnp.sqrt(Dh)
        if self.causal:
            cm = jnp.tril(jnp.ones((Sq, Sk), bool))
            s = jnp.where(cm[None, None], s, -1e30)
        if mask is not None:  # (B, Sk) valid-token mask
            s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        out = out.reshape(B, Sq, H * Dh).astype(self.dtype)
        return dense(self.d_model, "o_proj")(out)


class _MLP(nn.Module):
    d_model: int
    d_ff: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.d_ff, use_bias=False, dtype=self.dtype,
                     param_dtype=self.dtype, name="up_proj")(x)
        h = jax.nn.gelu(h)
        return nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                        param_dtype=self.dtype, name="down_proj")(h)


def _norm(name):
    return nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32, name=name)


# ---------------------------------------------------------------------------
# Encoder (BERT-family)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16


BERT_BASE = EncoderConfig()
BERT_LARGE = EncoderConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)
TINY_ENCODER = EncoderConfig(vocab_size=256, d_model=64, n_layers=2,
                             n_heads=4, d_ff=128, max_seq_len=64,
                             dtype=jnp.float32)


class EncoderBlock(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, mask):
        c = self.cfg
        h = _norm("ln_attn")(x).astype(c.dtype)
        x = x + _Attention(c.d_model, c.n_heads, c.dtype, name="attn")(
            h, mask=mask)
        h = _norm("ln_mlp")(x).astype(c.dtype)
        return x + _MLP(c.d_model, c.d_ff, c.dtype, name="mlp")(h)


class Encoder(nn.Module):
    """Bidirectional transformer encoder with an MLM head.

    __call__ returns (B, S, D) features; `mlm_logits` projects to vocab
    with the tied embedding; `pooled` mean-pools valid tokens for
    classification heads.
    """

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, tokens, mask=None):
        c = self.cfg
        embed = nn.Embed(c.vocab_size, c.d_model, dtype=c.dtype,
                         param_dtype=c.dtype, name="tok_embed")
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (c.max_seq_len, c.d_model), c.dtype)
        S = tokens.shape[1]
        x = embed(tokens) + pos[None, :S]
        if mask is None:
            mask = jnp.ones(tokens.shape, bool)
        for i in range(c.n_layers):
            x = EncoderBlock(c, name=f"layer_{i}")(x, mask)
        x = _norm("ln_final")(x)
        # Tied-embedding MLM logits.
        logits = embed.attend(x.astype(c.dtype))
        return x, logits

    @staticmethod
    def pooled(features, mask):
        m = mask[..., None].astype(features.dtype)
        return (features * m).sum(1) / jnp.maximum(m.sum(1), 1.0)


def mlm_loss(logits, targets, mlm_mask):
    """Cross-entropy only at masked positions (the BERT objective).
    One CE implementation lives in models/llama.py; this masks it."""
    from ray_tpu.models.llama import cross_entropy_loss

    return cross_entropy_loss(logits, targets, mask=mlm_mask)


# ---------------------------------------------------------------------------
# Encoder-decoder (T5-family)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EncDecConfig:
    vocab_size: int = 32128
    d_model: int = 768
    n_layers: int = 12          # per stack
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16


T5_BASE = EncDecConfig()
T5_LARGE = EncDecConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)
TINY_ENCDEC = EncDecConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                           d_ff=128, max_seq_len=64, dtype=jnp.float32)


class DecoderBlock(nn.Module):
    cfg: EncDecConfig

    @nn.compact
    def __call__(self, x, enc, enc_mask):
        c = self.cfg
        h = _norm("ln_self")(x).astype(c.dtype)
        x = x + _Attention(c.d_model, c.n_heads, c.dtype, causal=True,
                           name="self_attn")(h)
        h = _norm("ln_cross")(x).astype(c.dtype)
        x = x + _Attention(c.d_model, c.n_heads, c.dtype,
                           name="cross_attn")(h, kv=enc, mask=enc_mask)
        h = _norm("ln_mlp")(x).astype(c.dtype)
        return x + _MLP(c.d_model, c.d_ff, c.dtype, name="mlp")(h)


class EncoderDecoder(nn.Module):
    """Seq2seq transformer: bidirectional encoder, causal decoder with
    cross-attention (the T5 shape, pre-norm)."""

    cfg: EncDecConfig

    @nn.compact
    def __call__(self, src_tokens, tgt_tokens, src_mask=None):
        c = self.cfg
        embed = nn.Embed(c.vocab_size, c.d_model, dtype=c.dtype,
                         param_dtype=c.dtype, name="tok_embed")
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (c.max_seq_len, c.d_model), c.dtype)
        if src_mask is None:
            src_mask = jnp.ones(src_tokens.shape, bool)
        x = embed(src_tokens) + pos[None, : src_tokens.shape[1]]
        for i in range(c.n_layers):
            x = EncoderBlock(
                EncoderConfig(vocab_size=c.vocab_size, d_model=c.d_model,
                              n_heads=c.n_heads, d_ff=c.d_ff,
                              max_seq_len=c.max_seq_len, dtype=c.dtype),
                name=f"enc_{i}")(x, src_mask)
        enc = _norm("ln_enc")(x).astype(c.dtype)

        y = embed(tgt_tokens) + pos[None, : tgt_tokens.shape[1]]
        for i in range(c.n_layers):
            y = DecoderBlock(c, name=f"dec_{i}")(y, enc, src_mask)
        y = _norm("ln_dec")(y)
        return embed.attend(y.astype(c.dtype))


def seq2seq_loss(logits, targets, mask=None):
    """Alias of the shared CE (models/llama.py) under the seq2seq name."""
    from ray_tpu.models.llama import cross_entropy_loss

    return cross_entropy_loss(logits, targets, mask=mask)
