"""Mixture-of-Experts decoder (Mixtral-style), TPU-first with expert
parallelism.

The reference has no MoE / expert-parallel support at all (SURVEY.md §2.4:
EP "absent"); this is a native addition. Design follows the GSPMD MoE
idiom (Switch/GShard, public pattern): routing produces capacity-bounded
dispatch/combine one-hot tensors, expert FFNs are a single batched einsum
over a leading expert dimension, and *expert parallelism is a sharding*,
not message passing — the expert dimension of the weights and the
dispatched activations is sharded over the mesh axis `ep`
(`MOE_RULES`), so XLA inserts the all-to-alls over ICI.

Everything stays static-shape (capacity-bounded dispatch, no ragged
gather) so the whole step compiles onto the MXU.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (
    RMSNorm,
    apply_rope,
    rope_frequencies,
)
from ray_tpu.ops.attention import flash_attention, mha_reference, ring_attention
from ray_tpu.parallel.sharding import P, ShardingRules


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # "flash" | "ring" | "reference"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _maybe_constrain(x, spec):
    """with_sharding_constraint when a mesh is active; no-op otherwise
    (unit tests run the model without any mesh)."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or mesh.empty or not any(
                a in mesh.axis_names for a in ("ep", "tp")):
            return x
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception:
        return x


MIXTRAL_8X7B = MoEConfig()
TINY_MOE = MoEConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                     n_kv_heads=4, d_ff=128, n_experts=4, experts_per_token=2,
                     max_seq_len=128, dtype=jnp.float32,
                     attention="reference", remat=False)


class MoEAttention(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        B, S, _ = x.shape
        Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        dense = functools.partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                                  param_dtype=cfg.dtype)
        q = dense(Hq * Dh, name="q_proj")(x).reshape(B, S, Hq, Dh)
        k = dense(Hkv * Dh, name="k_proj")(x).reshape(B, S, Hkv, Dh)
        v = dense(Hkv * Dh, name="v_proj")(x).reshape(B, S, Hkv, Dh)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))

        cos, sin = rope_frequencies(Dh, cfg.max_seq_len, cfg.rope_theta)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        if Hkv != Hq:
            rep = Hq // Hkv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if cfg.attention == "flash":
            out = flash_attention(q, k, v, None, True)
        elif cfg.attention == "ring":
            out = ring_attention(q, k, v, axis="sp", causal=True)
        else:
            out = mha_reference(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, Hq * Dh)
        return dense(cfg.d_model, name="o_proj")(out)


class MoEMLP(nn.Module):
    """Top-k routed expert FFN with capacity-based dense dispatch.

    Dispatch/combine are einsums against one-hot (token, expert, slot)
    tensors; expert weights carry a leading E dim sharded over `ep`.
    """

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, M = x.shape
        E, K = cfg.n_experts, cfg.experts_per_token
        G = B * S
        # Per-expert slot budget; tokens routed past it are dropped (their
        # residual stream passes through unchanged).
        C = max(1, int(cfg.capacity_factor * G * K / E))

        xf = x.reshape(G, M)
        router = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router")
        logits = router(xf.astype(jnp.float32))          # (G, E)
        probs = jax.nn.softmax(logits, axis=-1)

        top_p, top_e = jax.lax.top_k(probs, K)           # (G, K)
        top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

        # Slot assignment: position of each (token, k) within its expert's
        # queue, computed with a cumsum over the flat token order.
        e_onehot = jax.nn.one_hot(top_e, E, dtype=jnp.float32)  # (G, K, E)
        # priority: k=0 choices fill before k=1 across all tokens
        flat = e_onehot.transpose(1, 0, 2).reshape(K * G, E)
        pos_in_expert = (jnp.cumsum(flat, axis=0) - flat)        # (K*G, E)
        pos = pos_in_expert.reshape(K, G, E).transpose(1, 0, 2)  # (G, K, E)
        slot = jnp.sum(pos * e_onehot, axis=-1).astype(jnp.int32)  # (G, K)
        keep = (slot < C).astype(jnp.float32)

        # dispatch: (G, E, C) one-hot; combine adds the gate probs.
        slot_oh = jax.nn.one_hot(slot, C, dtype=jnp.float32)     # (G, K, C)
        dispatch = jnp.einsum("gke,gkc,gk->gec", e_onehot, slot_oh, keep)
        combine = jnp.einsum("gec,gke,gk->gec", dispatch, e_onehot,
                             top_p * keep)

        # Load-balance aux loss (Switch eq. 4): E * Σ_e f_e · p_e.
        f_e = e_onehot.sum(axis=(0, 1)) / (G * K)                # (E,)
        p_e = probs.mean(axis=0)                                  # (E,)
        aux = E * jnp.sum(f_e * p_e) * cfg.aux_loss_coef
        self.sow("intermediates", "moe_aux_loss", aux)

        expert_in = jnp.einsum("gec,gm->ecm", dispatch,
                               xf.astype(jnp.float32)).astype(cfg.dtype)
        expert_in = _maybe_constrain(expert_in, P("ep", None, "tp"))

        # Batched expert FFN (SwiGLU), leading expert dim sharded over ep.
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (E, M, cfg.d_ff), cfg.dtype)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (E, M, cfg.d_ff), cfg.dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (E, cfg.d_ff, M), cfg.dtype)
        h = jnp.einsum("ecm,emf->ecf", expert_in, w_gate)
        u = jnp.einsum("ecm,emf->ecf", expert_in, w_up)
        out_e = jnp.einsum("ecf,efm->ecm", nn.silu(h) * u, w_down)

        out = jnp.einsum("gec,ecm->gm", combine,
                         out_e.astype(jnp.float32)).astype(cfg.dtype)
        return out.reshape(B, S, M)


class MoEDecoderLayer(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h = RMSNorm(cfg.rms_eps, name="input_norm")(x)
        x = x + MoEAttention(cfg, name="attn")(h, positions)
        h = RMSNorm(cfg.rms_eps, name="post_attn_norm")(x)
        x = x + MoEMLP(cfg, name="moe")(h)
        return x


class MoEModel(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, tokens, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     param_dtype=cfg.dtype, name="embed")(tokens)
        layer_cls = MoEDecoderLayer
        if cfg.remat:
            layer_cls = nn.remat(
                MoEDecoderLayer,
                policy=jax.checkpoint_policies.nothing_saveable)
        for i in range(cfg.n_layers):
            x = layer_cls(cfg, name=f"layers_{i}")(x, positions)
        x = RMSNorm(cfg.rms_eps, name="norm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=cfg.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def moe_aux_loss(intermediates) -> jnp.ndarray:
    """Sum the sown per-layer aux losses from apply(..., mutable=['intermediates'])."""
    leaves = jax.tree_util.tree_leaves(intermediates)
    if not leaves:
        return jnp.array(0.0, jnp.float32)
    return sum(jnp.asarray(l, jnp.float32).sum() for l in leaves)


# Sharding rules: transformer rules + expert weights sharded over ep (and
# tp/fsdp inside each expert). The router stays replicated.
MOE_RULES = ShardingRules([
    (r"embed/embedding", P("fsdp", "tp")),
    (r"(q_proj|k_proj|v_proj)/kernel", P("fsdp", "tp")),
    (r"o_proj/kernel", P("tp", "fsdp")),
    (r"router/kernel", P()),
    (r"(w_gate|w_up)$", P("ep", "fsdp", "tp")),
    (r"w_down$", P("ep", "tp", "fsdp")),
    (r"lm_head/kernel", P("fsdp", "tp")),
    (r"(norm|ln|scale|bias)", P()),
], default=P())


def count_flops_per_token(cfg: MoEConfig) -> float:
    """Active-parameter forward+backward FLOPs per token."""
    attn = (cfg.d_model * cfg.head_dim * (cfg.n_heads + 2 * cfg.n_kv_heads)
            + cfg.n_heads * cfg.head_dim * cfg.d_model)
    ffn_active = cfg.experts_per_token * 3 * cfg.d_model * cfg.d_ff
    n = (2 * cfg.vocab_size * cfg.d_model
         + cfg.n_layers * (attn + ffn_active))
    return 6.0 * n
