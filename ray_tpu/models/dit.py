"""DiT: diffusion transformer (image generation), TPU-first.

The reference framework ships no generative-image models (its model zoo is
RL-oriented; diffusion appears only in release-test user code) — this is a
framework-native family alongside the Llama decoder, MoE, and ViT: flax
modules sized for the MXU (head_dim 64-128, bf16), flash attention from
`ray_tpu.ops`, and a jittable DDPM noise-prediction loss + DDIM sampler so
training runs under the same `pjit` train-step machinery
(ray_tpu.train.spmd) as the language models.

Architecture follows the DiT recipe (Peebles & Xie 2022, public): patchify
→ N transformer blocks with adaptive layer norm conditioned on (timestep,
class) → unpatchify to the noise prediction.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, mha_reference


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    image_size: int = 32
    channels: int = 3
    patch_size: int = 4
    d_model: int = 384
    n_layers: int = 6
    n_heads: int = 6
    num_classes: int = 10          # 0 disables class conditioning
    timesteps: int = 1000
    dtype: Any = jnp.bfloat16
    attention: str = "flash"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding. t: (B,) float32 in [0, timesteps)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class AdaLNBlock(nn.Module):
    """Transformer block with adaLN-Zero conditioning (DiT block)."""

    cfg: DiTConfig

    @nn.compact
    def __call__(self, x, cond):
        cfg = self.cfg
        B, S, D = x.shape
        # 6 modulation vectors from the conditioning signal; the projection
        # initializes to zero so each block starts as identity (adaLN-Zero).
        mod = nn.Dense(6 * D, kernel_init=nn.initializers.zeros,
                       dtype=jnp.float32, name="adaLN")(nn.silu(cond))
        shift1, scale1, gate1, shift2, scale2, gate2 = jnp.split(
            mod[:, None, :], 6, axis=-1)

        h = nn.LayerNorm(use_bias=False, use_scale=False,
                         dtype=jnp.float32)(x)
        h = (h * (1 + scale1) + shift1).astype(cfg.dtype)
        qkv = nn.Dense(3 * D, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv.reshape(B, S, 3 * cfg.n_heads, cfg.head_dim)
                            .transpose(0, 2, 1, 3), 3, axis=1)
        if cfg.attention == "flash":
            attn = flash_attention(q, k, v)
        else:
            attn = mha_reference(q, k, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, S, D)
        attn = nn.Dense(D, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.dtype, name="proj")(attn)
        x = x + gate1.astype(cfg.dtype) * attn

        h = nn.LayerNorm(use_bias=False, use_scale=False,
                         dtype=jnp.float32)(x)
        h = (h * (1 + scale2) + shift2).astype(cfg.dtype)
        h = nn.Dense(4 * D, dtype=cfg.dtype, param_dtype=cfg.dtype,
                     name="mlp_in")(h)
        h = nn.Dense(D, dtype=cfg.dtype, param_dtype=cfg.dtype,
                     name="mlp_out")(nn.gelu(h))
        return x + gate2.astype(cfg.dtype) * h


class DiT(nn.Module):
    cfg: DiTConfig

    @nn.compact
    def __call__(self, images, t, labels=None):
        """images: (B, H, W, C) noisy input; t: (B,) timesteps;
        labels: (B,) int class ids or None. Returns predicted noise
        (B, H, W, C) in float32."""
        cfg = self.cfg
        B, H, W, C = images.shape
        p = cfg.patch_size
        # Patchify: (B, H/p * W/p, p*p*C)
        x = images.reshape(B, H // p, p, W // p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, cfg.num_patches,
                                                  p * p * C)
        x = nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=cfg.dtype,
                     name="patch_embed")(x.astype(cfg.dtype))
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, cfg.num_patches, cfg.d_model), cfg.dtype)
        x = x + pos

        cond = nn.Dense(cfg.d_model, dtype=jnp.float32, name="t_embed")(
            timestep_embedding(t, cfg.d_model))
        if cfg.num_classes and labels is not None:
            # Label dropout trains the unconditional branch for CFG; the
            # extra row is the null class.
            emb = nn.Embed(cfg.num_classes + 1, cfg.d_model,
                           dtype=jnp.float32, name="label_embed")
            cond = cond + emb(labels)

        for i in range(cfg.n_layers):
            x = AdaLNBlock(cfg, name=f"blocks_{i}")(x, cond)

        x = nn.LayerNorm(dtype=jnp.float32, name="final_norm")(
            x.astype(jnp.float32))
        x = nn.Dense(p * p * C, kernel_init=nn.initializers.zeros,
                     dtype=jnp.float32, name="final_proj")(x)
        # Unpatchify.
        x = x.reshape(B, H // p, W // p, p, p, C)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H, W, C)


# ---------------------------------------------------------------------------
# DDPM training + DDIM sampling
# ---------------------------------------------------------------------------


def diffusion_schedule(cfg: DiTConfig):
    """Cosine alpha-bar schedule (Nichol & Dhariwal)."""
    t = jnp.linspace(0, 1, cfg.timesteps + 1)
    f = jnp.cos((t + 0.008) / 1.008 * jnp.pi / 2) ** 2
    alpha_bar = f / f[0]
    return jnp.clip(alpha_bar, 1e-5, 1.0)


def ddpm_loss(model: DiT, params, images, labels, rng,
              label_drop_prob: float = 0.1):
    """Noise-prediction MSE at uniformly sampled timesteps."""
    cfg = model.cfg
    B = images.shape[0]
    rng_t, rng_n, rng_d = jax.random.split(rng, 3)
    t = jax.random.randint(rng_t, (B,), 0, cfg.timesteps)
    # Schedule has T+1 entries with alpha_bar[0] == 1 (zero noise); index
    # t+1 so every training sample carries noise to predict.
    alpha_bar = diffusion_schedule(cfg)[t + 1][:, None, None, None]
    noise = jax.random.normal(rng_n, images.shape, jnp.float32)
    noisy = jnp.sqrt(alpha_bar) * images + jnp.sqrt(1 - alpha_bar) * noise
    if cfg.num_classes and labels is not None:
        drop = jax.random.bernoulli(rng_d, label_drop_prob, (B,))
        labels = jnp.where(drop, cfg.num_classes, labels)  # null class
    pred = model.apply(params, noisy, t.astype(jnp.float32), labels)
    return jnp.mean((pred - noise) ** 2)


def ddim_sample(model: DiT, params, rng, *, num: int, steps: int = 50,
                labels=None, guidance: float = 0.0):
    """Deterministic DDIM sampler; classifier-free guidance when
    guidance > 0 and labels given. Fixed shapes / lax.scan — jittable."""
    cfg = model.cfg
    alpha_bar = diffusion_schedule(cfg)
    # Walk alpha_bar indices T..1; the final target index 0 (alpha_bar=1)
    # is x0 itself, so no step is wasted on a no-op.
    ts = jnp.linspace(cfg.timesteps, 1, steps).astype(jnp.int32)
    shape = (num, cfg.image_size, cfg.image_size, cfg.channels)
    x = jax.random.normal(rng, shape, jnp.float32)

    null = None if labels is None else jnp.full_like(labels, cfg.num_classes)

    def eps_fn(x, t_batch):
        eps = model.apply(params, x, t_batch, labels)
        if guidance > 0 and labels is not None:
            eps_u = model.apply(params, x, t_batch, null)
            eps = eps_u + (1 + guidance) * (eps - eps_u)
        return eps

    def body(x, i):
        t = ts[i]
        t_next = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1, steps - 1)], 0)
        ab_t = alpha_bar[t]
        ab_n = jnp.where(i + 1 < steps, alpha_bar[t_next], 1.0)
        # Training conditions on t with noise level alpha_bar[t+1]; here the
        # noise level is alpha_bar[t], so condition on t-1.
        t_batch = jnp.full((num,), t - 1, jnp.float32)
        eps = eps_fn(x, t_batch)
        x0 = (x - jnp.sqrt(1 - ab_t) * eps) / jnp.sqrt(ab_t)
        x0 = jnp.clip(x0, -3.0, 3.0)
        x = jnp.sqrt(ab_n) * x0 + jnp.sqrt(1 - ab_n) * eps
        return x, None

    x, _ = jax.lax.scan(body, x, jnp.arange(steps))
    return x


def count_dit_params(cfg: DiTConfig) -> int:
    D = cfg.d_model
    p2c = cfg.patch_size ** 2 * cfg.channels
    per_block = (
        6 * D * D + 6 * D          # adaLN kernel + bias
        + 3 * D * D                # qkv (no bias)
        + D * D                    # attn out proj (no bias)
        + 4 * D * D + 4 * D        # mlp_in kernel + bias
        + 4 * D * D + D)           # mlp_out kernel + bias
    extra = (
        p2c * D + D                # patch embed (+bias)
        + cfg.num_patches * D      # positional embedding
        + D * D + D                # t_embed
        + ((cfg.num_classes + 1) * D if cfg.num_classes else 0)
        + 2 * D                    # final_norm scale+bias
        + D * p2c + p2c)           # final proj (+bias)
    return cfg.n_layers * per_block + extra
