"""Llama-family decoder, TPU-first.

The reference trains LLMs only through external torch engines (its release
gates fine-tune GPT-J/vicuna via DeepSpeed/FSDP — reference:
release/release_tests.yaml:879,:891); the model itself is not part of the
framework. Here the flagship decoder IS part of the framework: flax.linen
modules whose parameter names line up with
`ray_tpu.parallel.TRANSFORMER_RULES` so TP/FSDP shardings apply by rule,
attention goes through the Pallas flash kernel (`ray_tpu.ops`), and
sequence parallelism swaps in ring attention under `shard_map`.

Conventions: activations (batch, seq, d_model), attention internals
(batch, heads, seq, head_dim), bfloat16 params optional, f32 RMSNorm.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import (
    flash_attention,
    mha_reference,
    ring_attention,
    ulysses_attention,
)


class PagedKVCache(NamedTuple):
    """Per-layer paged KV state for batched single-token decode.

    The KV cache is a shared pool of fixed-size pages (the vLLM block
    table idea, TPU-shaped — see ops/paged_attention.py); each sequence
    owns rows of `table`. HBM scales with resident tokens, not
    max_len x slots.
    """

    k_pool: Any    # (P, Hkv, page_size, D) — head-then-page minor layout
    v_pool: Any    # (P, Hkv, page_size, D)   (see ops/paged_attention.py)
    table: Any     # (B, NP) int32 pool indices per sequence
    length: Any    # (B,) int32 tokens already cached (= write offset)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # attention impl: "flash" (pallas), "ring" (sequence-parallel, inside
    # shard_map over axis sp), "reference" (plain jnp)
    attention: str = "flash"
    remat: bool = True
    # "full": recompute everything (nothing_saveable — min memory);
    # "dots": save matmul outputs, recompute elementwise (far less
    # recompute per backward at slightly more memory — usually the right
    # speed/memory point on TPU).
    remat_policy: str = "dots"
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


LLAMA2_7B = LlamaConfig()
LLAMA2_13B = LlamaConfig(d_model=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                         d_ff=13824)
LLAMA3_8B = LlamaConfig(vocab_size=128256, d_model=4096, n_layers=32,
                        n_heads=32, n_kv_heads=8, d_ff=14336,
                        rope_theta=500000.0, max_seq_len=8192)
TINY = LlamaConfig(vocab_size=256, d_model=128, n_layers=2, n_heads=4,
                   n_kv_heads=4, d_ff=256, max_seq_len=256,
                   dtype=jnp.float32, attention="reference", remat=False)


def rope_frequencies(head_dim: int, max_len: int, theta: float):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # (max_len, head_dim/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x, cos, sin, positions):
    """x: (B, H, S, D). positions: (B, S) or (S,)."""
    if positions.ndim == 1:
        cos_p = cos[positions][None, None]
        sin_p = sin[positions][None, None]
    else:
        cos_p = cos[positions][:, None]
        sin_p = sin[positions][:, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos_p - x2 * sin_p,
                           x2 * cos_p + x1 * sin_p], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                                  + self.eps)
        return (norm * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, kv_cache=None):
        cfg = self.cfg
        B, S, _ = x.shape
        Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        dense = functools.partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                                  param_dtype=cfg.dtype)
        q = dense(Hq * Dh, name="q_proj")(x).reshape(B, S, Hq, Dh)
        k = dense(Hkv * Dh, name="k_proj")(x).reshape(B, S, Hkv, Dh)
        v = dense(Hkv * Dh, name="v_proj")(x).reshape(B, S, Hkv, Dh)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))  # (B,H,S,D)

        cos, sin = rope_frequencies(Dh, cfg.max_seq_len, cfg.rope_theta)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        if isinstance(kv_cache, PagedKVCache):
            # Batched single-token decode over the shared page pool:
            # scatter this step's K/V into each sequence's current page,
            # then attend over its page table (GQA handled in-kernel; no
            # head repetition, no per-slot max_len cache).
            from ray_tpu.ops.paged_attention import (
                paged_decode_attention_batch)

            pc = kv_cache
            ps = pc.k_pool.shape[2]
            pages = jnp.take_along_axis(
                pc.table, (pc.length // ps)[:, None], axis=1)[:, 0]
            offs = pc.length % ps
            # pool is (P, Hkv, page, D): [pages, :, offs] scatters one
            # (B, Hkv, D) row set across the batch
            k_pool = pc.k_pool.at[pages, :, offs].set(k[:, :, 0, :])
            v_pool = pc.v_pool.at[pages, :, offs].set(v[:, :, 0, :])
            out = paged_decode_attention_batch(
                q[:, :, 0, :], k_pool, v_pool, pc.table, pc.length + 1)
            out = out[:, :, None, :].astype(cfg.dtype)
            out = out.transpose(0, 2, 1, 3).reshape(B, S, Hq * Dh)
            out = dense(cfg.d_model, name="o_proj")(out)
            return out, PagedKVCache(k_pool, v_pool, pc.table,
                                     pc.length + 1)

        new_cache = None
        if kv_cache is not None:
            # Decode step: append to cache (S == new tokens, typically 1).
            ck, cv, cache_len = kv_cache
            k = jax.lax.dynamic_update_slice_in_dim(ck, k, cache_len, axis=2)
            v = jax.lax.dynamic_update_slice_in_dim(cv, v, cache_len, axis=2)
            new_cache = (k, v, cache_len + S)

        if Hkv != Hq:  # GQA: repeat kv heads
            rep = Hq // Hkv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)

        if kv_cache is not None:
            # Decode attention over the cache with position masking.
            total = k.shape[2]
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) / jnp.sqrt(Dh)
            kpos = jnp.arange(total)[None, None, None, :]
            qpos = positions[:, None, :, None] if positions.ndim == 2 \
                else positions[None, None, :, None]
            s = jnp.where(kpos <= qpos, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", p,
                             v.astype(jnp.float32)).astype(cfg.dtype)
        elif cfg.attention == "flash":
            out = flash_attention(q, k, v, None, True)
        elif cfg.attention == "ring":
            out = ring_attention(q, k, v, axis="sp", causal=True)
        elif cfg.attention == "ulysses":
            out = ulysses_attention(q, k, v, axis="sp", causal=True)
        else:
            out = mha_reference(q, k, v, causal=True)

        out = out.transpose(0, 2, 1, 3).reshape(B, S, Hq * Dh)
        out = dense(cfg.d_model, name="o_proj")(out)
        if kv_cache is not None:
            return out, new_cache
        return out


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = functools.partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                                  param_dtype=cfg.dtype)
        gate = dense(cfg.d_ff, name="gate_proj")(x)
        up = dense(cfg.d_ff, name="up_proj")(x)
        return dense(cfg.d_model, name="down_proj")(nn.silu(gate) * up)


class DecoderLayer(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, kv_cache=None):
        cfg = self.cfg
        h = RMSNorm(cfg.rms_eps, name="input_norm")(x)
        if kv_cache is not None:
            attn, new_cache = Attention(cfg, name="attn")(h, positions, kv_cache)
        else:
            attn = Attention(cfg, name="attn")(h, positions)
            new_cache = None
        x = x + attn
        h = RMSNorm(cfg.rms_eps, name="post_attn_norm")(x)
        x = x + MLP(cfg, name="mlp")(h)
        if kv_cache is not None:
            return x, new_cache
        return x


class LlamaModel(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, kv_caches=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=cfg.dtype, name="embed")
        x = embed(tokens)
        layer_cls = DecoderLayer
        if cfg.remat and kv_caches is None:
            policy = (jax.checkpoint_policies.nothing_saveable
                      if cfg.remat_policy == "full" else
                      jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
            layer_cls = nn.remat(DecoderLayer, policy=policy)
        new_caches = []
        for i in range(cfg.n_layers):
            layer = layer_cls(cfg, name=f"layers_{i}")
            if kv_caches is not None:
                x, c = layer(x, positions, kv_caches[i])
                new_caches.append(c)
            else:
                x = layer(x, positions)
        x = RMSNorm(cfg.rms_eps, name="norm")(x)
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(cfg.dtype))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=cfg.dtype, name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        if kv_caches is not None:
            return logits, new_caches
        return logits


def init_kv_caches(cfg: LlamaConfig, batch: int, max_len: int):
    Dh = cfg.head_dim
    return [(jnp.zeros((batch, cfg.n_kv_heads, max_len, Dh), cfg.dtype),
             jnp.zeros((batch, cfg.n_kv_heads, max_len, Dh), cfg.dtype), 0)
            for _ in range(cfg.n_layers)]


def cross_entropy_loss(logits, targets, mask=None):
    # logsumexp form instead of materializing log_softmax: the full
    # (B,S,V) f32 normalized array never hits HBM — lse reduces
    # immediately (~2% MFU on v5e at d_model 2048/vocab 32k).
    l32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(l32, axis=-1)
    picked = jnp.take_along_axis(l32, targets[..., None], axis=-1)[..., 0]
    nll = lse - picked
    if mask is not None:
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
    return nll.mean()


def count_flops_per_token(cfg: LlamaConfig) -> float:
    """Approximate forward+backward FLOPs per token (6·N + attention)."""
    n = (cfg.vocab_size * cfg.d_model * (1 if cfg.tie_embeddings else 2)
         + cfg.n_layers * (
             cfg.d_model * cfg.head_dim * (cfg.n_heads + 2 * cfg.n_kv_heads)
             + cfg.n_heads * cfg.head_dim * cfg.d_model
             + 3 * cfg.d_model * cfg.d_ff))
    return 6.0 * n
