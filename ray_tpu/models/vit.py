"""Vision Transformer, TPU-first.

The reference ships no vision models (its release gates run torchvision
models through TorchTrainer — reference: release/release_tests.yaml air
batch-inference entries); here the vision family is part of the framework:
flax ViT whose parameter names line up with
`ray_tpu.parallel.TRANSFORMER_RULES` (q/k/v/o_proj, gate/up/down_proj) so
the same TP/FSDP rules shard it, and whose attention rides the same
Pallas flash kernel.

Conventions: images (batch, height, width, channels), patches flattened
to a (batch, tokens, d_model) sequence, bf16-friendly.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, mha_reference


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # or "reference"
    pool: str = "cls"  # or "mean"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


VIT_B16 = ViTConfig()
VIT_L16 = ViTConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)
VIT_TINY = ViTConfig(image_size=32, patch_size=8, num_classes=10, d_model=64,
                     n_layers=2, n_heads=4, d_ff=128, dtype=jnp.float32,
                     attention="reference")


class ViTAttention(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, _ = x.shape
        H, Dh = cfg.n_heads, cfg.head_dim
        dense = functools.partial(nn.Dense, use_bias=True, dtype=cfg.dtype,
                                  param_dtype=cfg.dtype)
        q = dense(H * Dh, name="q_proj")(x).reshape(B, T, H, Dh)
        k = dense(H * Dh, name="k_proj")(x).reshape(B, T, H, Dh)
        v = dense(H * Dh, name="v_proj")(x).reshape(B, T, H, Dh)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        if cfg.attention == "flash" and T % 128 == 0:
            out = flash_attention(q, k, v, None, False)
        else:
            out = mha_reference(q, k, v, causal=False)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
        return dense(cfg.d_model, name="o_proj")(out)


class ViTMLP(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = functools.partial(nn.Dense, use_bias=True, dtype=cfg.dtype,
                                  param_dtype=cfg.dtype)
        h = nn.gelu(dense(cfg.d_ff, name="up_proj")(x))
        return dense(cfg.d_model, name="down_proj")(h)


class ViTBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + ViTAttention(cfg, name="attn")(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        return x + ViTMLP(cfg, name="mlp")(h)


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        B = images.shape[0]
        # Patchify: a Conv with stride=patch is the canonical XLA-friendly
        # embedding (one big MXU matmul after im2col).
        x = nn.Conv(cfg.d_model, (cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    dtype=cfg.dtype, param_dtype=cfg.dtype,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.d_model)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.d_model), cfg.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, cfg.d_model)), x],
                            axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(0.02),
                         (1, cfg.num_patches + 1, cfg.d_model), cfg.dtype)
        x = x + pos
        for i in range(cfg.n_layers):
            x = ViTBlock(cfg, name=f"layers_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="norm")(x)
        pooled = x[:, 0] if cfg.pool == "cls" else x.mean(axis=1)
        logits = nn.Dense(cfg.num_classes, dtype=cfg.dtype,
                          param_dtype=cfg.dtype, name="lm_head")(pooled)
        return logits.astype(jnp.float32)


def vit_loss(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
