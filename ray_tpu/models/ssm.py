"""Selective state-space model (Mamba-family), TPU-first.

Rounds out the model zoo with the SSM architecture class. The TPU-native
angle: the recurrence h_t = a_t * h_{t-1} + b_t is evaluated with
`jax.lax.associative_scan` — O(log S) depth parallel prefix instead of a
sequential loop, which is the difference between MXU/VPU-friendly and
latency-bound on TPU. (Training/full-sequence forward only; an
incremental cached-state decode API is future work.)

Structure follows the Mamba block shape (Gu & Dao 2023, public
architecture): in-proj to a gated pair, short depthwise causal conv,
input-selective (Δ, B, C), diagonal A, gated out-proj. Implementation is
original and jnp-only.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SSMConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layers: int = 24
    d_state: int = 16          # per-channel SSM state size
    d_conv: int = 4            # depthwise conv width
    expand: int = 2            # inner width = expand * d_model
    dtype: Any = jnp.bfloat16

    @property
    def d_inner(self) -> int:
        return self.expand * self.d_model


MAMBA_130M = SSMConfig(d_model=768, n_layers=24)
MAMBA_790M = SSMConfig(d_model=1536, n_layers=48)
TINY_SSM = SSMConfig(vocab_size=256, d_model=64, n_layers=2, d_state=8,
                     expand=2, dtype=jnp.float32)


def _selective_scan(a, b):
    """First-order linear recurrence h_t = a_t * h_{t-1} + b_t over axis 1
    via parallel prefix. a, b: (B, S, E, N)."""

    def combine(left, right):
        a_l, b_l = left
        a_r, b_r = right
        return a_l * a_r, a_r * b_l + b_r

    _, h = jax.lax.associative_scan(combine, (a, b), axis=1)
    return h


class SSMBlock(nn.Module):
    cfg: SSMConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        B, S, _ = x.shape
        E, N = c.d_inner, c.d_state
        dense = lambda n, name, bias=False: nn.Dense(
            n, use_bias=bias, dtype=c.dtype, param_dtype=c.dtype, name=name)

        xz = dense(2 * E, "in_proj")(x)
        u, z = jnp.split(xz, 2, axis=-1)          # (B,S,E) each

        # Short depthwise causal conv (local mixing before the SSM).
        conv_w = self.param("conv_w", nn.initializers.normal(0.02),
                            (c.d_conv, E), c.dtype)
        u_pad = jnp.pad(u, ((0, 0), (c.d_conv - 1, 0), (0, 0)))
        u = sum(u_pad[:, i: i + S] * conv_w[i][None, None]
                for i in range(c.d_conv))
        u = jax.nn.silu(u)

        # Input-selective SSM parameters.
        delta = jax.nn.softplus(dense(E, "dt_proj", bias=True)(u))  # (B,S,E)
        Bsel = dense(N, "b_proj")(u)                                # (B,S,N)
        Csel = dense(N, "c_proj")(u)                                # (B,S,N)
        # Diagonal A < 0 for stability; log-parameterized.
        a_log = self.param("a_log", nn.initializers.normal(0.5), (E, N),
                           jnp.float32)
        A = -jnp.exp(a_log)                                          # (E,N)

        d32 = delta.astype(jnp.float32)
        decay = jnp.exp(d32[..., None] * A[None, None])              # (B,S,E,N)
        drive = (d32 * u.astype(jnp.float32))[..., None] * \
            Bsel.astype(jnp.float32)[:, :, None, :]                  # (B,S,E,N)
        h = _selective_scan(decay, drive)                            # (B,S,E,N)
        y = jnp.einsum("bsen,bsn->bse", h, Csel.astype(jnp.float32))
        D = self.param("d_skip", nn.initializers.ones, (E,), jnp.float32)
        y = (y + D[None, None] * u.astype(jnp.float32)).astype(c.dtype)

        y = y * jax.nn.silu(z)
        return dense(c.d_model, "out_proj")(y)


class SSMModel(nn.Module):
    """Decoder-only SSM language model (Mamba-style residual stack)."""

    cfg: SSMConfig

    @nn.compact
    def __call__(self, tokens):
        c = self.cfg
        embed = nn.Embed(c.vocab_size, c.d_model, dtype=c.dtype,
                         param_dtype=c.dtype, name="tok_embed")
        x = embed(tokens)
        for i in range(c.n_layers):
            h = nn.RMSNorm(epsilon=1e-5, dtype=jnp.float32,
                           name=f"norm_{i}")(x).astype(c.dtype)
            x = x + SSMBlock(c, name=f"block_{i}")(h)
        x = nn.RMSNorm(epsilon=1e-5, dtype=jnp.float32, name="norm_f")(x)
        return embed.attend(x.astype(c.dtype))
