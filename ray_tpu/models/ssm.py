"""Selective state-space model (Mamba-family), TPU-first.

Rounds out the model zoo with the SSM architecture class. The TPU-native
angle: the recurrence h_t = a_t * h_{t-1} + b_t is evaluated with
`jax.lax.associative_scan` — O(log S) depth parallel prefix instead of a
sequential loop, which is the difference between MXU/VPU-friendly and
latency-bound on TPU. Decode is O(1) per token: `init_ssm_state` /
`ssm_decode_step` carry the per-layer SSM state (E,N) and the depthwise
conv window (d_conv-1, E) — the SSM advantage over attention's O(S)
KV cache.

Structure follows the Mamba block shape (Gu & Dao 2023, public
architecture): in-proj to a gated pair, short depthwise causal conv,
input-selective (Δ, B, C), diagonal A, gated out-proj. Implementation is
original and jnp-only.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import P, ShardingRules


@dataclasses.dataclass(frozen=True)
class SSMConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layers: int = 24
    d_state: int = 16          # per-channel SSM state size
    d_conv: int = 4            # depthwise conv width
    expand: int = 2            # inner width = expand * d_model
    dtype: Any = jnp.bfloat16

    @property
    def d_inner(self) -> int:
        return self.expand * self.d_model


MAMBA_130M = SSMConfig(d_model=768, n_layers=24)
MAMBA_790M = SSMConfig(d_model=1536, n_layers=48)
TINY_SSM = SSMConfig(vocab_size=256, d_model=64, n_layers=2, d_state=8,
                     expand=2, dtype=jnp.float32)


def _selective_scan(a, b):
    """First-order linear recurrence h_t = a_t * h_{t-1} + b_t over axis 1
    via parallel prefix. a, b: (B, S, E, N)."""

    def combine(left, right):
        a_l, b_l = left
        a_r, b_r = right
        return a_l * a_r, a_r * b_l + b_r

    _, h = jax.lax.associative_scan(combine, (a, b), axis=1)
    return h


class SSMBlock(nn.Module):
    cfg: SSMConfig

    @nn.compact
    def __call__(self, x, state=None, return_state: bool = False):
        """state=None: full-sequence parallel forward -> y, or
        (y, final_state) with return_state=True (the O(log S) prefill —
        sequential per-token priming would be exactly the latency-bound
        pattern the scan exists to avoid).
        state=(conv_window, h): O(1) single-token step (S must be 1)
        -> (y, new_state). conv_window: (B, d_conv-1, E) last pre-conv
        activations; h: (B, E, N) f32 SSM state."""
        c = self.cfg
        B, S, _ = x.shape
        E, N = c.d_inner, c.d_state
        dense = lambda n, name, bias=False: nn.Dense(
            n, use_bias=bias, dtype=c.dtype, param_dtype=c.dtype, name=name)

        xz = dense(2 * E, "in_proj")(x)
        u_in, z = jnp.split(xz, 2, axis=-1)       # (B,S,E) each

        # Short depthwise causal conv (local mixing before the SSM).
        conv_w = self.param("conv_w", nn.initializers.normal(0.02),
                            (c.d_conv, E), c.dtype)
        if state is None:
            u_pad = jnp.pad(u_in, ((0, 0), (c.d_conv - 1, 0), (0, 0)))
            # Next decode step needs the last d_conv-1 pre-conv activations.
            window = u_pad[:, S:]
            u = sum(u_pad[:, i: i + S] * conv_w[i][None, None]
                    for i in range(c.d_conv))
        else:
            if S != 1:
                raise ValueError(
                    f"stateful SSM step requires S==1, got S={S}; prime a "
                    "prompt with the parallel forward (return_state=True)")
            conv_state, h_prev = state
            window = jnp.concatenate([conv_state, u_in], axis=1)  # (B,d_conv,E)
            u = sum(window[:, i: i + 1] * conv_w[i][None, None]
                    for i in range(c.d_conv))                      # (B,1,E)
        u = jax.nn.silu(u)

        # Input-selective SSM parameters.
        delta = jax.nn.softplus(dense(E, "dt_proj", bias=True)(u))  # (B,S,E)
        Bsel = dense(N, "b_proj")(u)                                # (B,S,N)
        Csel = dense(N, "c_proj")(u)                                # (B,S,N)
        # Diagonal A < 0 for stability; log-parameterized.
        a_log = self.param("a_log", nn.initializers.normal(0.5), (E, N),
                           jnp.float32)
        A = -jnp.exp(a_log)                                          # (E,N)

        d32 = delta.astype(jnp.float32)
        decay = jnp.exp(d32[..., None] * A[None, None])              # (B,S,E,N)
        drive = (d32 * u.astype(jnp.float32))[..., None] * \
            Bsel.astype(jnp.float32)[:, :, None, :]                  # (B,S,E,N)
        if state is None:
            h = _selective_scan(decay, drive)                        # (B,S,E,N)
        else:
            h_new = decay[:, 0] * h_prev + drive[:, 0]               # (B,E,N)
            h = h_new[:, None]
        y = jnp.einsum("bsen,bsn->bse", h, Csel.astype(jnp.float32))
        D = self.param("d_skip", nn.initializers.ones, (E,), jnp.float32)
        y = (y + D[None, None] * u.astype(jnp.float32)).astype(c.dtype)

        y = y * jax.nn.silu(z)
        out = dense(c.d_model, "out_proj")(y)
        if state is not None:
            return out, (window[:, 1:], h_new)
        if return_state:
            return out, (window, h[:, -1])
        return out


class SSMModel(nn.Module):
    """Decoder-only SSM language model (Mamba-style residual stack)."""

    cfg: SSMConfig

    @nn.compact
    def __call__(self, tokens, states=None, return_states: bool = False):
        """states=None: (B,S) -> (B,S,V) logits; with return_states=True
        -> (logits, states) — the parallel PREFILL priming decode.
        states=[per-layer (conv_window, h)]: (B,1) single-token decode ->
        (logits (B,1,V), new_states). Build fresh states with
        init_ssm_state or prime them with the prefill form."""
        c = self.cfg
        embed = nn.Embed(c.vocab_size, c.d_model, dtype=c.dtype,
                         param_dtype=c.dtype, name="tok_embed")
        x = embed(tokens)
        new_states = []
        for i in range(c.n_layers):
            h = nn.RMSNorm(epsilon=1e-5, dtype=jnp.float32,
                           name=f"norm_{i}")(x).astype(c.dtype)
            block = SSMBlock(c, name=f"block_{i}")
            if states is not None:
                y, st = block(h, states[i])
            elif return_states:
                y, st = block(h, return_state=True)
            else:
                y, st = block(h), None
            x = x + y
            if st is not None:
                new_states.append(st)
        x = nn.RMSNorm(epsilon=1e-5, dtype=jnp.float32, name="norm_f")(x)
        logits = embed.attend(x.astype(c.dtype))
        if states is None and not return_states:
            return logits
        return logits, new_states


# Mesh sharding rules (same idiom as TRANSFORMER_RULES/MOE_RULES): TP
# shards the inner channel dim E, FSDP the other matrix dim; the tiny
# d_state axis stays replicated.
SSM_RULES = ShardingRules([
    (r"tok_embed/embedding", P("fsdp", "tp")),
    (r"in_proj/kernel", P("fsdp", "tp")),
    (r"out_proj/kernel", P("tp", "fsdp")),
    (r"dt_proj/kernel", P("fsdp", "tp")),
    (r"(b_proj|c_proj)/kernel", P("fsdp", None)),
    (r"conv_w", P(None, "tp")),
    (r"a_log", P("tp", None)),
    (r"d_skip", P("tp")),
    (r"(norm|scale|bias)", P()),
], default=P())


def init_ssm_state(cfg: SSMConfig, batch: int):
    """Fresh per-layer decode state: conv window + SSM state, all zeros
    (the attention-KV-cache analog, but O(1) in sequence length)."""
    E, N = cfg.d_inner, cfg.d_state
    return [(jnp.zeros((batch, cfg.d_conv - 1, E), cfg.dtype),
             jnp.zeros((batch, E, N), jnp.float32))
            for _ in range(cfg.n_layers)]


def ssm_prefill(model: SSMModel, params, tokens):
    """Prime decode state from a prompt in ONE parallel forward (O(log S)
    scan depth): tokens (B,S) -> (last_logits (B,V), states)."""
    logits, states = model.apply(params, tokens, return_states=True)
    return logits[:, -1], states


def ssm_decode_step(model: SSMModel, params, token, states):
    """One O(1) decode step: token (B,) -> (logits (B,V), new_states).
    jit this; the state pytree has static shapes independent of position."""
    logits, new_states = model.apply(params, token[:, None], states)
    return logits[:, 0], new_states
