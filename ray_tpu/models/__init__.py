"""Model families shipped with the framework (TPU-native flax modules).

The reference ships no model implementations (its release gates pull
GPT-J/vicuna through external torch engines); here the flagship decoder,
an expert-parallel MoE, and the generation path are part of the framework.
"""

from ray_tpu.models.llama import (
    LLAMA2_7B,
    LLAMA2_13B,
    LLAMA3_8B,
    TINY,
    LlamaConfig,
    LlamaModel,
    cross_entropy_loss,
    init_kv_caches,
)
from ray_tpu.models.moe import (
    MIXTRAL_8X7B,
    MOE_RULES,
    TINY_MOE,
    MoEConfig,
    MoEModel,
    moe_aux_loss,
)
from ray_tpu.models.dit import (
    DiT,
    DiTConfig,
    ddim_sample,
    ddpm_loss,
)
from ray_tpu.models.encoder import (
    BERT_BASE,
    BERT_LARGE,
    T5_BASE,
    T5_LARGE,
    TINY_ENCDEC,
    TINY_ENCODER,
    EncDecConfig,
    Encoder,
    EncoderConfig,
    EncoderDecoder,
    mlm_loss,
    seq2seq_loss,
)
from ray_tpu.models.generate import Generator, SamplingParams, generate
from ray_tpu.models.ssm import (
    MAMBA_130M,
    MAMBA_790M,
    TINY_SSM,
    SSM_RULES,
    SSMConfig,
    SSMModel,
    init_ssm_state,
    ssm_decode_step,
    ssm_prefill,
)
from ray_tpu.models.vit import (
    VIT_B16,
    VIT_L16,
    VIT_TINY,
    ViT,
    ViTConfig,
    vit_loss,
)

__all__ = [
    "LlamaConfig", "LlamaModel", "LLAMA2_7B", "LLAMA2_13B", "LLAMA3_8B",
    "TINY", "cross_entropy_loss", "init_kv_caches",
    "MoEConfig", "MoEModel", "MIXTRAL_8X7B", "TINY_MOE", "MOE_RULES",
    "moe_aux_loss",
    "Generator", "SamplingParams", "generate",
    "ViT", "ViTConfig", "VIT_B16", "VIT_L16", "VIT_TINY", "vit_loss",
    "DiT", "DiTConfig", "ddpm_loss", "ddim_sample",
    "Encoder", "EncoderConfig", "BERT_BASE", "BERT_LARGE", "TINY_ENCODER",
    "mlm_loss", "EncoderDecoder", "EncDecConfig", "T5_BASE", "T5_LARGE",
    "TINY_ENCDEC", "seq2seq_loss",
    "SSMModel", "SSMConfig", "MAMBA_130M", "MAMBA_790M", "TINY_SSM",
    "SSM_RULES", "init_ssm_state", "ssm_decode_step", "ssm_prefill",
]
