"""Autoregressive generation: KV-cache prefill + jitted decode steps.

The reference serves models through external engines; generation here is
native and TPU-shaped: one compiled prefill program (full prompt through
the Pallas flash path writes the KV caches) and one compiled single-token
decode program reused every step — static shapes throughout, so each is
compiled exactly once per (batch, max_len) bucket.

Sampling: greedy, temperature, top-k, nucleus (top-p).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.llama import LlamaConfig, LlamaModel, init_kv_caches


@dataclass(frozen=True)
class SamplingParams:
    max_new_tokens: int = 64
    temperature: float = 0.0   # 0 → greedy
    top_k: int = 0             # 0 → disabled
    top_p: float = 1.0         # 1 → disabled
    eos_token: int | None = None


def sample_logits(logits, rng, params: SamplingParams):
    """logits: (B, V) → tokens (B,)."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / params.temperature
    if params.top_k > 0:
        kth = jax.lax.top_k(logits, params.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest set whose mass ≥ top_p; keep at least one.
        cutoff_idx = jnp.sum(cum < params.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


class Generator:
    """Holds the compiled prefill/decode programs for one (model, bucket).

    Usage::

        gen = Generator(cfg, params, batch=1, max_len=512)
        out = gen.generate(prompt_tokens, SamplingParams(max_new_tokens=32))
    """

    def __init__(self, cfg: LlamaConfig, params, *, batch: int,
                 max_len: int, rng_seed: int = 0):
        self.cfg = cfg
        self.params = params
        self.batch = batch
        self.max_len = max_len
        self.model = LlamaModel(cfg)
        self._rng = jax.random.PRNGKey(rng_seed)

        model = self.model

        @jax.jit
        def prefill(params, tokens, prompt_len, caches):
            # tokens: (B, max_prompt) right-padded; positions mask pads.
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape)
            logits, caches = model.apply(params, tokens, positions,
                                         kv_caches=caches)
            # Logits at the last real prompt token per row.
            last = jnp.take_along_axis(
                logits, (prompt_len - 1)[:, None, None], axis=1)[:, 0]
            return last, caches

        @jax.jit
        def decode_step(params, token, pos, caches):
            # token: (B,), pos: (B,) absolute position of `token`.
            logits, caches = model.apply(
                params, token[:, None], pos[:, None], kv_caches=caches)
            return logits[:, 0], caches

        self._prefill = prefill
        self._decode = decode_step

    def _fresh_caches(self):
        return init_kv_caches(self.cfg, self.batch, self.max_len)

    def generate(self, prompt_tokens, params: SamplingParams | None = None
                 ) -> np.ndarray:
        """prompt_tokens: (B, S) array/list, all rows full width S.
        Returns (B, max_new_tokens) (shorter if eos_token ends all rows)."""
        sp = params or SamplingParams()
        prompts = np.asarray(prompt_tokens, dtype=np.int32)
        if prompts.ndim == 1:
            prompts = prompts[None]
        B, S = prompts.shape
        assert B == self.batch, f"generator built for batch={self.batch}"
        assert S + sp.max_new_tokens <= self.max_len, "bucket too small"
        prompt_len = jnp.full((B,), S, jnp.int32)

        caches = self._fresh_caches()
        logits, caches = self._prefill(self.params, jnp.asarray(prompts),
                                       prompt_len, caches)
        out = np.zeros((B, sp.max_new_tokens), np.int32)
        pos = jnp.full((B,), S, jnp.int32)
        rng = self._rng
        finished = np.zeros((B,), bool)
        token = None
        for i in range(sp.max_new_tokens):
            rng, step_rng = jax.random.split(rng)
            token = sample_logits(logits, step_rng, sp)
            tok_np = np.asarray(token)
            out[:, i] = tok_np
            if sp.eos_token is not None:
                finished |= tok_np == sp.eos_token
                if finished.all():
                    out = out[:, : i + 1]
                    break
            if i + 1 < sp.max_new_tokens:
                logits, caches = self._decode(self.params, token, pos, caches)
                pos = pos + 1
        self._rng = rng
        return out


def generate(cfg: LlamaConfig, params, prompt_tokens,
             sampling: SamplingParams | None = None, *,
             max_len: int | None = None) -> np.ndarray:
    """One-shot convenience wrapper around Generator."""
    prompts = np.asarray(prompt_tokens, dtype=np.int32)
    if prompts.ndim == 1:
        prompts = prompts[None]
    sp = sampling or SamplingParams()
    bucket = max_len or min(cfg.max_seq_len,
                            prompts.shape[1] + sp.max_new_tokens)
    gen = Generator(cfg, params, batch=prompts.shape[0], max_len=bucket)
    return gen.generate(prompts, sp)
