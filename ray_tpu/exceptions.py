"""Public exception hierarchy.

Parity: python/ray/exceptions.py in the reference (RayError, RayTaskError,
RayActorError, GetTimeoutError, ObjectLostError, ...).
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Re-raised at `ray_tpu.get` with the remote traceback attached
    (reference: python/ray/exceptions.py RayTaskError)."""

    def __init__(self, cause: BaseException, remote_traceback: str = "",
                 task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_name = task_name
        super().__init__(
            f"task {task_name!r} failed: {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{remote_traceback}"
        )


class ActorError(RayTpuError):
    """An actor died before or while executing a submitted method
    (reference: RayActorError)."""


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    """All copies of an object were lost and it could not be reconstructed
    (reference: ObjectLostError / ObjectReconstructionFailedError)."""

    def __init__(self, object_id_hex: str, message: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(message or f"object {object_id_hex} lost")


class OwnerDiedError(ObjectLostError):
    pass


class DeviceObjectLostError(ObjectLostError):
    """The worker pinning a device-resident object (HBM tensor) died or
    dropped the pin before a consumer resolved it. Owners recover via
    lineage reconstruction (the creating task re-executes and re-pins);
    borrowers observe this error."""


class TaskCancelledError(RayTpuError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass
