"""Dashboard: HTTP observability endpoint for the cluster.

Parity: reference dashboard/ (aiohttp head server + React SPA, modules:
node, actor, job, state, metrics — dashboard/head.py). Here a stdlib
threading HTTP server exposes the same data as JSON under /api/* plus a
single self-contained HTML page; it runs inside any connected process
(`ray_tpu.dashboard.start()`, or `ray_tpu dashboard` from the CLI).

Endpoints: /api/version /api/nodes /api/actors /api/jobs /api/tasks
/api/summary /api/cluster_status /api/submission_jobs /api/logs
/api/grafana/dashboard (generated Grafana JSON, metrics-module parity)
/logs/view?node=&name= /api/stacks /api/worker_stats (the last four are
the reference's log + reporter module data views: per-node log browser
with tail, all-worker stack dumps, per-worker cpu/rss).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
body { font-family: system-ui, sans-serif; margin: 2rem; background: #fafafa; }
h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
table { border-collapse: collapse; margin-top: .5rem; }
td, th { border: 1px solid #ccc; padding: .25rem .6rem; font-size: .85rem; }
th { background: #eee; text-align: left; }
#err { color: #b00; }
</style></head><body>
<h1>ray_tpu dashboard</h1><div id="err"></div>
<div id="sections"></div>
<script>
const SECTIONS = [
  ["Cluster", "/api/cluster_status"], ["Nodes", "/api/nodes"],
  ["Actors", "/api/actors"], ["Jobs", "/api/jobs"],
  ["Submission jobs", "/api/submission_jobs"],
  ["Placement groups", "/api/placement_groups"],
  ["Serve deployments", "/api/serve"],
  ["Workflows", "/api/workflows"],
  ["Task summary", "/api/summary"],
  ["Worker stats (cpu/rss)", "/api/worker_stats"],
  ["Logs", "/api/logs"]];
function table(rows) {
  if (!Array.isArray(rows)) rows = [rows];
  if (!rows.length) return "<i>none</i>";
  const keys = Object.keys(rows[0]);
  let h = "<table><tr>" + keys.map(k => `<th>${k}</th>`).join("") + "</tr>";
  for (const r of rows) h += "<tr>" + keys.map(k => {
    const v = r[k];
    if (k === "view" && typeof v === "string")
      return `<td><a href="${v}" target="_blank">view</a></td>`;
    return `<td>${JSON.stringify(v)}</td>`;
  }).join("") + "</tr>";
  return h + "</table>";
}
async function refresh() {
  let html = "";
  for (const [name, url] of SECTIONS) {
    try {
      const data = await (await fetch(url)).json();
      html += `<h2>${name}</h2>` + table(data);
    } catch (e) { document.getElementById("err").textContent = String(e); }
  }
  document.getElementById("sections").innerHTML = html;
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


def _json_default(o):
    try:
        return o.item()  # numpy scalars
    except AttributeError:
        return str(o)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from ray_tpu.util import state

        path = self.path.split("?")[0].rstrip("/") or "/"
        try:
            if path == "/":
                return self._send(200, _PAGE.encode(), "text/html")
            if path == "/metrics":
                from ray_tpu.util.metrics import (core_prometheus_text,
                                                  prometheus_text)

                body = prometheus_text() + core_prometheus_text()
                return self._send(200, body.encode(),
                                  "text/plain; version=0.0.4")
            if path == "/api/version":
                import ray_tpu

                data = {"version": ray_tpu.__version__}
            elif path == "/api/nodes":
                data = state.list_nodes()
            elif path == "/api/actors":
                data = state.list_actors()
            elif path == "/api/jobs":
                data = state.list_jobs()
            elif path == "/api/tasks":
                data = state.list_tasks()
            elif path == "/api/summary":
                data = state.summarize_tasks()
            elif path == "/api/cluster_status":
                data = state.cluster_status()
            elif path == "/api/submission_jobs":
                from ray_tpu.job_submission import JobSubmissionClient

                data = [j.__dict__ for j in JobSubmissionClient().list_jobs()]
            elif path == "/api/placement_groups":
                data = state.list_placement_groups()
            elif path == "/api/objects":
                data = state.list_objects()
            elif path == "/api/serve":
                # Serve module (reference: dashboard/modules/serve): the
                # controller's deployment table. Only "no controller"
                # means serve-is-down; a wedged controller must surface
                # as an error, not render as an empty table.
                import ray_tpu
                from ray_tpu.serve.controller import CONTROLLER_NAME

                try:
                    controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                                   namespace="serve")
                except ValueError:  # named actor not found
                    data = {}
                else:
                    data = ray_tpu.get(
                        controller.list_deployments.remote(), timeout=5)
            elif path == "/api/workflows":
                from ray_tpu import workflow

                data = [{"workflow_id": w, "status": workflow.get_status(w)}
                        for w in workflow.list_workflows()]
            elif path == "/api/logs":
                import urllib.parse

                # Log index with view links (reference: dashboard log
                # module's per-node file browser). Names are URL-quoted —
                # '&'/'#'/'\"'/spaces in a filename must not break the
                # query string or the href attribute.
                data = []
                for node in state.list_logs():
                    for f in node.get("logs", []):
                        data.append({
                            "node": node.get("node_id", "?")[:8],
                            "file": f["name"], "size": f["size"],
                            "view": (f"/logs/view?node="
                                     f"{node.get('node_id', '')}&name="
                                     + urllib.parse.quote(f["name"],
                                                          safe=""))})
            elif path == "/logs/view":
                import urllib.parse

                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query)
                node = (q.get("node") or [""])[0]
                name = (q.get("name") or [""])[0]
                out = state.tail_log(node, name)
                body = out.get("data", out.get("error", "")) or ""
                return self._send(200, body.encode(), "text/plain")
            elif path == "/api/stacks":
                # All-worker stack dumps per node (reference:
                # dashboard/modules/reporter profiling views / ray stack).
                data = state.dump_stacks()
            elif path == "/api/profile":
                # Live statistical CPU profile of every worker
                # (?duration=seconds; reference: the reporter module's
                # py-spy profiling endpoint — workers self-sample here).
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query)
                dur = float((q.get("duration") or ["2"])[0])
                data = state.profile_workers(duration_s=min(dur, 30.0))
            elif path == "/api/grafana/dashboard":
                # Generated Grafana dashboard JSON (reference:
                # dashboard/modules/metrics grafana_dashboard_factory).
                from ray_tpu.util.grafana import generate_dashboard

                data = generate_dashboard()
            elif path == "/api/worker_stats":
                data = []
                for node in state.worker_stats():
                    nid = node.get("node_id", "?")[:8]
                    data.append({"node": nid, "worker_id": "(raylet)",
                                 "pid": node.get("pid"),
                                 "cpu_s": node.get("cpu_s"),
                                 "rss_mb": round(
                                     node.get("rss_bytes", 0) / 2**20, 1)})
                    for w in node.get("workers", []):
                        data.append({
                            "node": nid,
                            "worker_id": w["worker_id"][:8],
                            "pid": w.get("pid"),
                            "cpu_s": w.get("cpu_s"),
                            "rss_mb": round(
                                w.get("rss_bytes", 0) / 2**20, 1)})
            else:
                return self._send(404, b'{"error": "not found"}',
                                  "application/json")
            body = json.dumps(data, default=_json_default).encode()
            return self._send(200, body, "application/json")
        except Exception as e:  # noqa: BLE001
            body = json.dumps({"error": str(e)}).encode()
            return self._send(500, body, "application/json")


_server: ThreadingHTTPServer | None = None


def start(host: str = "127.0.0.1", port: int = 8265) -> int:
    """Start the dashboard server; returns the bound port (the reference's
    default dashboard port is also 8265)."""
    global _server
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="ray_tpu-dashboard")
    t.start()
    return _server.server_address[1]


def stop() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
