"""Dashboard: HTTP observability endpoint for the cluster.

Parity: reference dashboard/ (aiohttp head server + React SPA, modules:
node, actor, job, state, metrics — dashboard/head.py +
dashboard/client/src). Here a stdlib threading HTTP server exposes the
same data as JSON under /api/* and serves a dependency-free hash-routed
SPA from `dashboard_static/` (overview cards, per-entity drill-down,
sortable/filterable tables, log tailing, live profiling); it runs
inside any connected process (`ray_tpu.dashboard.start()`, or
`ray_tpu dashboard` from the CLI).

Endpoints: /api/version /api/nodes /api/node_stats /api/actors
/api/jobs /api/tasks /api/summary[/actors|/objects|/task_latency|
/device_objects] /api/device_objects /api/pump_stats /api/cluster_status
/api/submission_jobs[/logs?id=] /api/logs /api/events
/api/grafana/dashboard (generated Grafana JSON, metrics-module parity)
/logs/view?node=&name= /api/stacks /api/profile /api/worker_stats (the
reference's log + reporter module data views: per-node log browser with
tail, all-worker stack dumps, live CPU sampling, per-worker cpu/rss).
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_STATIC_DIR = os.path.join(os.path.dirname(__file__), "dashboard_static")
_STATIC_TYPES = {".html": "text/html", ".js": "text/javascript",
                 ".css": "text/css", ".json": "application/json"}

# (monotonic timestamp, merged events) — see _merged_events().
_events_cache: tuple[float, list] = (float("-inf"), [])
_events_lock = threading.Lock()


def _merged_events(state) -> list:
    """Merged structured cluster events (reference: dashboard event
    module over src/ray/util/event.h emitters). The events-*.jsonl files
    live in each raylet's own session log dir, so they're read through
    the raylets' log endpoints — THIS cluster's events regardless of
    temp_dir overrides or other clusters on the box.

    The SPA polls every 3 s; the TTL (longer than the poll period, or it
    would never hit) plus a single-flight lock keeps a passive Events
    tab from becoming continuous cluster-wide I/O. Event file names
    embed the emitter (events-gcs / events-raylet-<id8>), so raylets
    sharing one session dir list the same files — each unique file is
    tailed once, batched into one RPC per node."""
    import time as _time

    global _events_cache
    ts, cached = _events_cache
    if _time.monotonic() - ts < 10.0:
        return cached
    if not _events_lock.acquire(blocking=False):
        return cached  # another request is already rebuilding
    try:
        per_node: dict[str, list[str]] = {}
        seen: set[str] = set()
        for node in state.list_logs():
            nid = node.get("node_id", "")
            for f in node.get("logs", []):
                name = f["name"]
                if (name in seen or not name.startswith("events-")
                        or not name.endswith(".jsonl")):
                    continue
                seen.add(name)
                per_node.setdefault(nid, []).append(name)
        data = []
        for nid, names in per_node.items():
            for out in state.tail_logs(nid, names,
                                       max_bytes=256 << 10).values():
                for line in (out.get("data") or "").splitlines():
                    try:
                        data.append(json.loads(line))
                    except ValueError:
                        continue
        data.sort(key=lambda e: e.get("ts", 0))
        data = data[-500:]
        _events_cache = (_time.monotonic(), data)
        return data
    finally:
        _events_lock.release()


class _MetricsHistory:
    """In-memory time-series ring (reference: dashboard/modules/metrics/
    — there Prometheus+Grafana render history; here the head samples its
    own cluster view so the SPA can chart without external infra).
    One sampler thread per dashboard server; 600 samples @2s = 20 min."""

    def __init__(self, interval_s: float = 2.0, maxlen: int = 600):
        from collections import deque

        self.interval_s = interval_s
        self.samples: "deque[dict]" = deque(maxlen=maxlen)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_leases: dict[str, float] = {}

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ray_tpu-metrics-history")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _loop(self):
        import time as _time

        from ray_tpu.util import state
        while not self._stop.wait(self.interval_s):
            try:
                nodes = {}
                lease_rate = 0.0
                for n in state.node_stats():
                    nid = n.get("node_id", "?")
                    total = n.get("total", {})
                    avail = n.get("available", {})
                    granted = float(n.get("leases_granted", 0))
                    prev = self._last_leases.get(nid)
                    if prev is not None:
                        lease_rate += max(0.0, granted - prev) \
                            / self.interval_s
                    self._last_leases[nid] = granted
                    nodes[nid[:8]] = {
                        "cpu_used": round(total.get("CPU", 0)
                                          - avail.get("CPU", 0), 2),
                        "cpu_total": total.get("CPU", 0),
                        "workers": n.get("num_workers", 0),
                        "store_mb": round(n.get("store", {}).get(
                            "bytes_in_use", 0) / 2**20, 1),
                        "pending_leases": n.get("pending_leases", 0),
                    }
                self.samples.append({
                    "ts": _time.time(),
                    "nodes": nodes,
                    "task_rate_per_s": round(lease_rate, 1),
                })
            except Exception:
                continue  # cluster mid-teardown; keep sampling

    def snapshot(self) -> dict:
        return {"interval_s": self.interval_s,
                "samples": list(self.samples)}


_metrics_history: _MetricsHistory | None = None


def _json_default(o):
    try:
        return o.item()  # numpy scalars
    except AttributeError:
        return str(o)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from ray_tpu.util import state

        path = self.path.split("?")[0].rstrip("/") or "/"
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        try:
            if path == "/" or path.startswith("/static/"):
                # SPA shell + assets. Names are restricted to a flat
                # basename inside dashboard_static (no traversal).
                name = ("index.html" if path == "/"
                        else os.path.basename(path[len("/static/"):]))
                full = os.path.join(_STATIC_DIR, name)
                ctype = _STATIC_TYPES.get(os.path.splitext(name)[1])
                if ctype is None or not os.path.isfile(full):
                    return self._send(404, b"not found", "text/plain")
                with open(full, "rb") as f:
                    return self._send(200, f.read(), ctype)
            if path == "/metrics":
                from ray_tpu.util.metrics import (core_prometheus_text,
                                                  prometheus_text)

                # core first: it sets the pump gauges and synchronously
                # flushes the registry to the GCS (metrics.
                # flush_registry_now), so prometheus_text renders THIS
                # scrape's values — the reverse order (or the throttled
                # async flush alone) served the previous scrape's.
                core = core_prometheus_text()
                body = prometheus_text() + core
                return self._send(200, body.encode(),
                                  "text/plain; version=0.0.4")
            if path == "/api/version":
                import ray_tpu

                data = {"version": ray_tpu.__version__}
            elif path == "/api/nodes":
                data = state.list_nodes()
            elif path == "/api/actors":
                data = state.list_actors()
            elif path == "/api/jobs":
                data = state.list_jobs()
            elif path == "/api/tasks":
                data = state.list_tasks()
            elif path == "/api/summary":
                data = state.summarize_tasks()
            elif path == "/api/summary/actors":
                data = state.summarize_actors()
            elif path == "/api/summary/objects":
                data = state.summarize_objects()
            elif path == "/api/summary/native_control":
                # Native control plane health: GCS actor plane + every
                # raylet lease plane — fallthrough/degraded counters,
                # stale-epoch rejections, divergence-breaker state.
                data = state.summarize_native_control()
            elif path == "/api/summary/task_latency":
                # Per-stage lifecycle latency percentiles (SUBMITTED →
                # LEASE_* → DISPATCHED → ARGS_FETCHED → RUNNING →
                # FINISHED) from the GCS task-event table. Bounded by
                # default — the endpoint is polled, and dragging the
                # full 200k-row table over RPC per request would make
                # the GCS spend its loop time packing event batches.
                limit = int((q.get("limit") or ["20000"])[0])
                data = state.summarize_task_latency(
                    limit=max(1, min(limit, 500000)))
            elif path == "/api/pump_stats":
                # Daemon event-loop stats: per-handler call counts +
                # latencies for the GCS/raylet pumps (event_stats.h
                # analogue) and the native in-pump service counters.
                data = state.pump_stats()
            elif path == "/api/node_stats":
                data = state.node_stats(
                    node_id=(q.get("node") or [None])[0])
            elif path == "/api/events":
                data = _merged_events(state)
            elif path == "/api/cluster_status":
                data = state.cluster_status()
            elif path == "/api/submission_jobs":
                from ray_tpu.job_submission import JobSubmissionClient

                data = [j.__dict__ for j in JobSubmissionClient().list_jobs()]
            elif path == "/api/submission_jobs/logs":
                from ray_tpu.job_submission import JobSubmissionClient

                sid = (q.get("id") or [""])[0]
                try:
                    out = JobSubmissionClient().get_job_logs(sid)
                except ValueError as e:  # unknown submission id
                    return self._send(404, str(e).encode(), "text/plain")
                return self._send(200, (out or "").encode(), "text/plain")
            elif path == "/api/placement_groups":
                data = state.list_placement_groups()
            elif path == "/api/objects":
                data = state.list_objects()
            elif path == "/api/device_objects":
                # Device object plane: pinned-HBM registries per worker
                # (raylet fan-out), transfer-route counters, owned
                # descriptors (_private/device_objects.py).
                data = state.list_device_objects(
                    entries=(q.get("entries") or ["1"])[0] != "0")
            elif path == "/api/summary/device_objects":
                data = state.summarize_device_objects()
            elif path == "/api/serve":
                # Serve module (reference: dashboard/modules/serve): the
                # controller's deployment table. Only "no controller"
                # means serve-is-down; a wedged controller must surface
                # as an error, not render as an empty table.
                import ray_tpu
                from ray_tpu.serve.controller import CONTROLLER_NAME

                try:
                    controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                                   namespace="serve")
                except ValueError:  # named actor not found
                    data = {}
                else:
                    data = ray_tpu.get(
                        controller.list_deployments.remote(), timeout=5)
            elif path == "/api/workflows":
                from ray_tpu import workflow

                data = [{"workflow_id": w, "status": workflow.get_status(w)}
                        for w in workflow.list_workflows()]
            elif path == "/api/logs":
                # Log index with view links (reference: dashboard log
                # module's per-node file browser). Names are URL-quoted —
                # '&'/'#'/'\"'/spaces in a filename must not break the
                # query string or the href attribute. ?node= narrows the
                # fan-out to one raylet (the SPA's node-detail view
                # refreshes every 3 s — it must not ping every node).
                only = (q.get("node") or [None])[0]
                data = []
                for node in state.list_logs(node_id=only):
                    nid = node.get("node_id", "")
                    for f in node.get("logs", []):
                        data.append({
                            "node": (nid or "?")[:8], "node_id": nid,
                            "file": f["name"], "size": f["size"],
                            "view": (f"/logs/view?node={nid}&name="
                                     + urllib.parse.quote(f["name"],
                                                          safe=""))})
            elif path == "/logs/view":
                node = (q.get("node") or [""])[0]
                name = (q.get("name") or [""])[0]
                out = state.tail_log(node, name)
                body = out.get("data", out.get("error", "")) or ""
                return self._send(200, body.encode(), "text/plain")
            elif path == "/api/stacks":
                # All-worker stack dumps per node (reference:
                # dashboard/modules/reporter profiling views / ray stack).
                data = state.dump_stacks()
            elif path == "/api/profile":
                # Live statistical CPU profile of every worker
                # (?duration=seconds; reference: the reporter module's
                # py-spy profiling endpoint — workers self-sample here).
                dur = float((q.get("duration") or ["2"])[0])
                data = state.profile_workers(duration_s=min(dur, 30.0))
            elif path == "/api/grafana/dashboard":
                # Generated Grafana dashboard JSON (reference:
                # dashboard/modules/metrics grafana_dashboard_factory).
                from ray_tpu.util.grafana import generate_dashboard

                data = generate_dashboard()
            elif path == "/api/metrics/history":
                data = (_metrics_history.snapshot()
                        if _metrics_history is not None
                        else {"interval_s": 0, "samples": []})
            elif path == "/api/worker_stats":
                # Flat per-worker rows; node_id is the FULL id (the SPA's
                # node-detail view filters on it), "node" the display
                # prefix. ?node= narrows the fan-out to one raylet.
                data = []
                for node in state.worker_stats(
                        node_id=(q.get("node") or [None])[0]):
                    nid = node.get("node_id", "")
                    data.append({"node": (nid or "?")[:8], "node_id": nid,
                                 "worker_id": "(raylet)",
                                 "pid": node.get("pid"),
                                 "cpu_s": node.get("cpu_s"),
                                 "rss_mb": round(
                                     node.get("rss_bytes", 0) / 2**20, 1)})
                    for w in node.get("workers", []):
                        data.append({
                            "node": (nid or "?")[:8], "node_id": nid,
                            "worker_id": w["worker_id"][:8],
                            "pid": w.get("pid"),
                            "actor": w.get("actor_id", "")[:8],
                            "leased": w.get("leased"),
                            "blocked": w.get("blocked"),
                            "cpu_s": w.get("cpu_s"),
                            "rss_mb": round(
                                w.get("rss_bytes", 0) / 2**20, 1)})
            else:
                return self._send(404, b'{"error": "not found"}',
                                  "application/json")
            body = json.dumps(data, default=_json_default).encode()
            return self._send(200, body, "application/json")
        except Exception as e:  # noqa: BLE001
            body = json.dumps({"error": str(e)}).encode()
            return self._send(500, body, "application/json")


_server: ThreadingHTTPServer | None = None


def start(host: str = "127.0.0.1", port: int = 8265) -> int:
    """Start the dashboard server; returns the bound port (the reference's
    default dashboard port is also 8265)."""
    global _server, _metrics_history
    if _server is not None:
        return _server.server_address[1]
    _server = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(target=_server.serve_forever, daemon=True,
                         name="ray_tpu-dashboard")
    t.start()
    _metrics_history = _MetricsHistory()
    _metrics_history.start()
    return _server.server_address[1]


def stop() -> None:
    global _server, _events_cache, _metrics_history
    if _metrics_history is not None:
        _metrics_history.stop()
        _metrics_history = None
    if _server is not None:
        _server.shutdown()
        _server = None
    # Drop cached events: the next start() may serve a different cluster.
    _events_cache = (float("-inf"), [])
