"""Scheduling strategies for tasks and actors.

Parity: reference python/ray/util/scheduling_strategies.py
("DEFAULT"/"SPREAD" strings, PlacementGroupSchedulingStrategy,
NodeAffinitySchedulingStrategy).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

DEFAULT = "DEFAULT"
SPREAD = "SPREAD"


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False
