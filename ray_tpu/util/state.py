"""State observability API: list/summarize cluster entities.

Parity: reference python/ray/experimental/state/api.py (`ray list
tasks/actors/objects/nodes/...`, `ray summary`), backed by the GCS task
manager (reference: gcs_task_manager.cc) and node/actor tables.
"""

from __future__ import annotations

from collections import Counter

from ray_tpu._private.api_internal import get_core_worker


def list_nodes() -> list[dict]:
    cw = get_core_worker()
    return cw._run(cw.gcs.call("GetAllNodes", {}))["nodes"]


def list_actors() -> list[dict]:
    cw = get_core_worker()
    return cw._run(cw.gcs.call("ListActors", {}))["actors"]


def list_jobs() -> list[dict]:
    cw = get_core_worker()
    return cw._run(cw.gcs.call("ListJobs", {}))["jobs"]


def list_placement_groups() -> list[dict]:
    cw = get_core_worker()
    return cw._run(cw.gcs.call("ListPlacementGroups", {}))["placement_groups"]


def list_tasks(limit: int = 1000) -> list[dict]:
    """Latest known state per task, from the GCS task-event buffer."""
    cw = get_core_worker()
    events = cw._run(cw.gcs.call("ListTaskEvents", {"limit": limit * 4}))["events"]
    latest: dict[str, dict] = {}
    for e in events:
        latest[e["task_id"]] = e
    return list(latest.values())[-limit:]


def dump_stacks() -> list[dict]:
    """All-thread stacks of every worker on every node (reference:
    `ray stack`, scripts.py:2453)."""
    return _per_node_call("NodeStacks", timeout=30)


def profile_workers(duration_s: float = 2.0) -> list[dict]:
    """Live statistical CPU profile of every worker on every node
    (reference: dashboard reporter py-spy profiling hooks): each worker
    samples its own frames for duration_s; results aggregate hot stacks
    per worker."""
    return _per_node_call("NodeProfile", payload={"duration_s": duration_s},
                          timeout=duration_s + 30)


def node_stats(node_id: str | None = None) -> list[dict]:
    """Per-raylet core stats (workers, leases, store, spilling) pulled
    concurrently from every alive node — the data source for the
    dashboard's core metrics (parity: reference per-node stats via the
    dashboard reporter agent); `node_id` narrows the fan-out to one
    raylet."""
    return _per_node_call("GetState", node_id=node_id, timeout=10)


def _per_node_call(method: str, payload: dict | None = None,
                   node_id: str | None = None, timeout: float = 15.0
                   ) -> list[dict]:
    """Fan a raylet RPC out to every alive node (or one) concurrently."""
    import asyncio

    from ray_tpu._private import rpc

    cw = get_core_worker()
    nodes = [n for n in cw._run(cw.gcs.call("GetAllNodes", {}))["nodes"]
             if n.get("alive") and (node_id is None
                                    or n["node_id"] == node_id)]

    async def one(n):
        try:
            conn = await rpc.connect(n["host"], n["raylet_port"],
                                     name=f"state-{method}")
            try:
                return await conn.call(method, payload or {},
                                       timeout=timeout)
            finally:
                await conn.close()
        except Exception as e:
            return {"node_id": n["node_id"],
                    "error": f"{type(e).__name__}: {e}"}

    async def collect():
        return list(await asyncio.gather(*(one(n) for n in nodes)))

    return cw._run(collect())


def list_logs(node_id: str | None = None) -> list[dict]:
    """Per-node log-file index (reference: dashboard log module /
    `ray logs`)."""
    return _per_node_call("ListLogs", node_id=node_id)


def tail_log(node_id: str, name: str, max_bytes: int = 64 << 10) -> dict:
    """Tail one log file on one node."""
    out = _per_node_call("TailLog", {"name": name, "max_bytes": max_bytes},
                         node_id=node_id)
    return out[0] if out else {"error": f"node {node_id} not found"}


def tail_logs(node_id: str, names: list[str],
              max_bytes: int = 64 << 10) -> dict[str, dict]:
    """Tail several log files on one node with a single RPC (returns
    {name: tail-result}); the dashboard's event merge depends on this
    not paying a connection per file."""
    out = _per_node_call("TailLog", {"names": names, "max_bytes": max_bytes},
                         node_id=node_id)
    return out[0].get("files", {}) if out else {}


def worker_stats(node_id: str | None = None) -> list[dict]:
    """Per-worker CPU/RSS across the cluster (reference:
    dashboard/modules/reporter per-node stats); `node_id` narrows the
    fan-out to one raylet."""
    return _per_node_call("WorkerStats", node_id=node_id)


def list_objects() -> list[dict]:
    """Objects owned by the calling process (cluster-wide listing requires
    per-raylet scans; see `summarize_objects`)."""
    cw = get_core_worker()
    out = []
    for oid_hex, o in cw.objects.items():
        out.append({
            "object_id": oid_hex,
            "state": o.state,
            "size": o.size,
            "locations": sorted(o.locations),
            "inline": o.inline is not None,
            "local_refs": o.local_refs,
            "submitted_refs": o.submitted_refs,
        })
    return out


def summarize_tasks() -> dict:
    by_state = Counter()
    by_name = Counter()
    for t in list_tasks(limit=100000):
        by_state[t["state"]] += 1
        by_name[t["name"]] += 1
    return {"by_state": dict(by_state), "by_name": dict(by_name)}


def summarize_actors() -> dict:
    by_state = Counter(a["state"] for a in list_actors())
    return {"by_state": dict(by_state)}


def summarize_objects() -> dict:
    """Owner-reported object counts and bytes by state (parity:
    `ray summary objects`)."""
    from collections import Counter

    by_state = Counter()
    total_bytes = 0
    for o in list_objects():
        by_state[o.get("state", "?")] += 1
        total_bytes += int(o.get("size") or 0)
    return {"by_state": dict(by_state), "total_bytes": total_bytes}


def cluster_status() -> dict:
    cw = get_core_worker()
    return cw._run(cw.gcs.call("GetClusterStatus", {}))
