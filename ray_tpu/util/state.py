"""State observability API: list/summarize cluster entities.

Parity: reference python/ray/experimental/state/api.py (`ray list
tasks/actors/objects/nodes/...`, `ray summary`), backed by the GCS task
manager (reference: gcs_task_manager.cc) and node/actor tables.
"""

from __future__ import annotations

from collections import Counter

from ray_tpu._private.api_internal import (
    _client_fallback, core_worker_or_none, get_core_worker)


def _gcs_call(method: str, payload: dict | None = None) -> dict:
    """One GCS RPC, from wherever this process sits: through the local
    CoreWorker's session when there is one, else proxied over the
    client connection's ClientGcsCall passthrough (reference: the state
    API works under ray://). Raylet fan-outs (_per_node_call) stay
    core-worker-only — a client machine cannot dial raylets directly."""
    cw = core_worker_or_none()
    if cw is not None:
        return cw._run(cw.gcs.call(method, payload or {}))
    ctx = _client_fallback()
    if ctx is not None:
        return ctx.gcs_call(method, payload or {})
    get_core_worker()  # raises the canonical "not initialized" error
    raise AssertionError("unreachable")


def list_nodes() -> list[dict]:
    return _gcs_call("GetAllNodes")["nodes"]


def list_actors() -> list[dict]:
    return _gcs_call("ListActors")["actors"]


def list_jobs() -> list[dict]:
    return _gcs_call("ListJobs")["jobs"]


def list_placement_groups() -> list[dict]:
    return _gcs_call("ListPlacementGroups")["placement_groups"]


# Ordered lifecycle ladder (reference: gcs.proto TaskStatus). Owner-side
# stamps: SUBMITTED, LEASE_*, DISPATCHED, FINISHED/FAILED; executor-side:
# ARGS_FETCHED, RUNNING; GCS-side: actor CREATE_* stages.
LIFECYCLE_STAGES = ("SUBMITTED", "LEASE_REQUESTED", "LEASE_GRANTED",
                    "DISPATCHED", "ARGS_FETCHED", "RUNNING",
                    "FINISHED", "FAILED")
_STAGE_RANK = {s: i for i, s in enumerate(LIFECYCLE_STAGES)}


def list_tasks(limit: int = 1000) -> list[dict]:
    """Latest known state per task, from the GCS task-event buffer.
    Events for one task arrive from several processes (owner, executor,
    GCS), so "latest" is by timestamp with the ladder rank as the
    tie-break, not by arrival order."""
    events = _gcs_call("ListTaskEvents", {"limit": limit * 8})["events"]
    latest: dict[str, dict] = {}
    for e in events:
        cur = latest.get(e["task_id"])
        if cur is None or (e.get("ts", 0.0), _STAGE_RANK.get(e.get("state"), -1)) \
                >= (cur.get("ts", 0.0), _STAGE_RANK.get(cur.get("state"), -1)):
            latest[e["task_id"]] = e
    return list(latest.values())[-limit:]


def dump_stacks() -> list[dict]:
    """All-thread stacks of every worker on every node (reference:
    `ray stack`, scripts.py:2453)."""
    return _per_node_call("NodeStacks", timeout=30)


def profile_workers(duration_s: float = 2.0) -> list[dict]:
    """Live statistical CPU profile of every worker on every node
    (reference: dashboard reporter py-spy profiling hooks): each worker
    samples its own frames for duration_s; results aggregate hot stacks
    per worker."""
    return _per_node_call("NodeProfile", payload={"duration_s": duration_s},
                          timeout=duration_s + 30)


def debug_tasks(node_id: str | None = None) -> list[dict]:
    """Per-worker submission-state dump: owned pending tasks and lease
    slots from every worker, plus each raylet's lease table — the
    debug_state.txt analog (reference: node_manager.cc DumpDebugState).
    This is the tool that diagnosed the nested-fanout wedge; `node_id`
    narrows the fan-out to one raylet."""
    return _per_node_call("NodeDebugTasks", node_id=node_id, timeout=30)


def node_stats(node_id: str | None = None) -> list[dict]:
    """Per-raylet core stats (workers, leases, store, spilling) pulled
    concurrently from every alive node — the data source for the
    dashboard's core metrics (parity: reference per-node stats via the
    dashboard reporter agent); `node_id` narrows the fan-out to one
    raylet."""
    return _per_node_call("GetState", node_id=node_id, timeout=10)


def _per_node_call(method: str, payload: dict | None = None,
                   node_id: str | None = None, timeout: float = 15.0
                   ) -> list[dict]:
    """Fan a raylet RPC out to every alive node (or one) concurrently."""
    import asyncio

    from ray_tpu._private import rpc

    cw = get_core_worker()
    nodes = [n for n in cw._run(cw.gcs.call("GetAllNodes", {}))["nodes"]
             if n.get("alive") and (node_id is None
                                    or n["node_id"] == node_id)]

    async def one(n):
        try:
            conn = await rpc.dial(n["host"], n["raylet_port"],
                                  name=f"state-{method}", timeout=5.0)
            try:
                return await conn.call(method, payload or {},
                                       timeout=timeout)
            finally:
                await conn.close()
        except Exception as e:
            return {"node_id": n["node_id"],
                    "error": f"{type(e).__name__}: {e}"}

    async def collect():
        return list(await asyncio.gather(*(one(n) for n in nodes)))

    return cw._run(collect())


def list_logs(node_id: str | None = None) -> list[dict]:
    """Per-node log-file index (reference: dashboard log module /
    `ray logs`)."""
    return _per_node_call("ListLogs", node_id=node_id)


def tail_log(node_id: str, name: str, max_bytes: int = 64 << 10) -> dict:
    """Tail one log file on one node."""
    out = _per_node_call("TailLog", {"name": name, "max_bytes": max_bytes},
                         node_id=node_id)
    return out[0] if out else {"error": f"node {node_id} not found"}


def tail_logs(node_id: str, names: list[str],
              max_bytes: int = 64 << 10) -> dict[str, dict]:
    """Tail several log files on one node with a single RPC (returns
    {name: tail-result}); the dashboard's event merge depends on this
    not paying a connection per file."""
    out = _per_node_call("TailLog", {"names": names, "max_bytes": max_bytes},
                         node_id=node_id)
    return out[0].get("files", {}) if out else {}


def worker_stats(node_id: str | None = None) -> list[dict]:
    """Per-worker CPU/RSS across the cluster (reference:
    dashboard/modules/reporter per-node stats); `node_id` narrows the
    fan-out to one raylet."""
    return _per_node_call("WorkerStats", node_id=node_id)


def list_objects() -> list[dict]:
    """Objects owned by the calling process (cluster-wide listing requires
    per-raylet scans; see `summarize_objects`)."""
    cw = get_core_worker()
    out = []
    for oid_hex, o in cw.objects.items():
        out.append({
            "object_id": oid_hex,
            "state": o.state,
            "size": o.size,
            "locations": sorted(o.locations),
            "inline": o.inline is not None,
            "local_refs": o.local_refs,
            "submitted_refs": o.submitted_refs,
        })
    return out


def summarize_tasks() -> dict:
    by_state = Counter()
    by_name = Counter()
    for t in list_tasks(limit=100000):
        by_state[t["state"]] += 1
        by_name[t["name"]] += 1
    return {"by_state": dict(by_state), "by_name": dict(by_name)}


def summarize_actors() -> dict:
    by_state = Counter(a["state"] for a in list_actors())
    return {"by_state": dict(by_state)}


def summarize_objects() -> dict:
    """Owner-reported object counts and bytes by state (parity:
    `ray summary objects`)."""
    from collections import Counter

    by_state = Counter()
    total_bytes = 0
    for o in list_objects():
        by_state[o.get("state", "?")] += 1
        total_bytes += int(o.get("size") or 0)
    return {"by_state": dict(by_state), "total_bytes": total_bytes}


def summarize_native_control() -> dict:
    """Native control plane health across the cluster: the GCS actor
    plane's counters (GetClusterStatus) plus every raylet lease
    plane's (GetState) — handled/fallthrough/degraded totals, the
    stale-epoch rejection count, divergence-breaker state and the
    per-method handled/routed/degraded split."""
    out = {"gcs": _gcs_call("GetClusterStatus").get("native_control"),
           "raylets": []}
    for st in node_stats():
        if "error" in st:
            continue
        out["raylets"].append({"node_id": st.get("node_id"),
                               "native_control": st.get("native_control")})
    return out


def cluster_status() -> dict:
    out = _gcs_call("GetClusterStatus")
    # Elastic-training counters: fold the published ray_tpu_train_*
    # gauges (trainer drivers push running totals) into the status blob
    # so `ray_tpu status` shows resize/steps-lost health next to the
    # node table.
    try:
        from ray_tpu.util.metrics import get_metrics_snapshot

        totals: dict[str, float] = {}
        for snap in get_metrics_snapshot().values():
            for name, m in snap.items():
                if not name.startswith("ray_tpu_train_"):
                    continue
                for v in (m.get("values") or {}).values():
                    totals[name] = totals.get(name, 0) + v
        if totals:
            out["train_elastic"] = totals
    except Exception:
        pass
    return out


def list_device_objects(entries: bool = True) -> dict:
    """Device object plane state (parity target: `ray list objects` for
    GPU objects): this process's pin registry + transfer counters, the
    owned device-object descriptors (object id → pin worker / bytes /
    leaf count), and every node's per-worker registry stats via raylet
    fan-out."""
    from ray_tpu._private import device_objects

    cw = get_core_worker()
    local = device_objects.registry().stats()
    if entries:
        local["entries"] = device_objects.registry().entries()
    owned = []
    for oid_hex, o in list(cw.objects.items()):
        d = getattr(o, "device", None)
        if d:
            owned.append({
                "object_id": oid_hex,
                "state": o.state,
                "pin_worker": (d[0][2][:12] if d[0] else "(local)"),
                "pin_node": (d[0][3][:12] if d[0] else ""),
                "key_prefix": d[1],
                "pinned_bytes": d[2],
                "leaves": d[3],
                "local_refs": o.local_refs,
                "submitted_refs": o.submitted_refs,
            })
    return {"local": local, "owned": owned,
            "nodes": _per_node_call("NodeDeviceObjects",
                                    payload={"entries": bool(entries)})}


def summarize_device_objects() -> dict:
    """Cluster-wide pinned-HBM totals per node from the device plane."""
    out = list_device_objects(entries=False)
    per_node = []
    total_bytes = total_objects = 0
    for node in out["nodes"]:
        if "error" in node:
            per_node.append(node)
            continue
        nb = sum(w.get("pinned_bytes", 0) for w in node.get("workers", []))
        no = sum(w.get("pinned_objects", 0) for w in node.get("workers", []))
        total_bytes += nb
        total_objects += no
        per_node.append({"node_id": node.get("node_id"),
                         "pinned_bytes": nb, "pinned_objects": no})
    return {"pinned_bytes": total_bytes, "pinned_objects": total_objects,
            "owned_descriptors": len(out["owned"]),
            "local_counters": out["local"]["counters"],
            "per_node": per_node}


# ---------- task-lifecycle latency breakdown ----------

# (stage_name, from_state, to_state): duration of each ladder segment.
# `total` spans submission to terminal state.
LATENCY_STAGES = (
    ("queue_to_lease_request", "SUBMITTED", "LEASE_REQUESTED"),
    ("lease_negotiation", "LEASE_REQUESTED", "LEASE_GRANTED"),
    ("dispatch", "LEASE_GRANTED", "DISPATCHED"),
    ("args_fetch", "DISPATCHED", "ARGS_FETCHED"),
    ("startup", "ARGS_FETCHED", "RUNNING"),
    ("execution", "RUNNING", None),       # None = FINISHED or FAILED
    ("total", "SUBMITTED", None),
)


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


def summarize_task_latency(limit: int = 200000,
                           events: list[dict] | None = None) -> dict:
    """Per-stage latency percentiles across the task-event table
    (reference analog: `ray summary tasks` backed by gcs_task_manager's
    per-state timestamps). Returns {"tasks": n, "stages": {stage:
    {count, p50_ms, p95_ms, p99_ms, mean_ms, max_ms}}}; a stage is
    reported only for tasks that recorded both of its endpoints, so
    actor tasks (no lease stages) and failed tasks mix freely with the
    plain-task ladder."""
    if events is None:
        events = _gcs_call("ListTaskEvents", {"limit": limit})["events"]
    # (min, max) stamp per (task, state): pre-execution stages pair the
    # FIRST pass's stamps (what the submission experienced); the
    # execution stage pairs the terminal stamp with the LAST RUNNING at
    # or before it, so a task that failed once and finished on retry
    # doesn't book the whole retry gap as user-code execution.
    per_task: dict[str, dict[str, tuple[float, float]]] = {}
    for e in events:
        stamps = per_task.setdefault(e["task_id"], {})
        state = e.get("state")
        ts = e.get("ts")
        if state is None or ts is None:
            continue
        cur = stamps.get(state)
        stamps[state] = (ts, ts) if cur is None else \
            (min(cur[0], ts), max(cur[1], ts))
    samples: dict[str, list[float]] = {name: [] for name, _, _ in
                                       LATENCY_STAGES}
    for stamps in per_task.values():
        terminal = stamps.get("FINISHED", stamps.get("FAILED"))
        terminal = terminal and terminal[0]
        for name, frm, to in LATENCY_STAGES:
            span0 = stamps.get(frm)
            if span0 is None:
                continue
            t0 = span0[0]
            if to is None:
                t1 = terminal
                if name == "execution" and t1 is not None \
                        and span0[1] <= t1:
                    t0 = span0[1]  # last attempt's RUNNING
            else:
                t1 = stamps.get(to) and stamps[to][0]
            if t1 is not None:
                samples[name].append(max(0.0, t1 - t0))
    stages = {}
    for name, vals in samples.items():
        if not vals:
            continue
        vals.sort()
        stages[name] = {
            "count": len(vals),
            "p50_ms": round(_percentile(vals, 0.50) * 1000, 3),
            "p95_ms": round(_percentile(vals, 0.95) * 1000, 3),
            "p99_ms": round(_percentile(vals, 0.99) * 1000, 3),
            "mean_ms": round(sum(vals) / len(vals) * 1000, 3),
            "max_ms": round(vals[-1] * 1000, 3),
        }
    return {"tasks": len(per_task), "stages": stages}


def pump_stats() -> dict:
    """Event-loop/RPC dispatch stats of every daemon: the GCS pump
    (per-handler latencies + native in-pump service counters) and each
    raylet's pump. The Python-side analogue of the reference's
    event_stats.h surface (`RAY_event_stats=1` debug state dump)."""
    cw = get_core_worker()
    gcs = cw._run(cw.gcs.call("GetEventLoopStats", {}, timeout=10))
    return {"gcs": gcs, "raylets": _per_node_call("GetEventLoopStats")}
