"""joblib backend running jobs as cluster tasks.

Parity: reference python/ray/util/joblib/ — `register_ray()` installs a
`ray` parallel backend so scikit-learn-style `Parallel(n_jobs=...)`
fan-outs run on the cluster:

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray"):
        Parallel()(delayed(f)(x) for x in xs)
"""

from __future__ import annotations

import ray_tpu

__all__ = ["register_ray"]


def _call(func):
    return func()


class _RayFuture:
    """Future-like handle joblib polls via .get(timeout)."""

    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout=None):
        return ray_tpu.get(self._ref, timeout=timeout)


def register_ray() -> None:
    """Register the 'ray' joblib backend."""
    try:
        from joblib._parallel_backends import ParallelBackendBase
        from joblib.parallel import register_parallel_backend
    except ImportError as e:  # pragma: no cover - joblib is a soft dep
        raise ImportError(
            "joblib is required for register_ray(); pip install joblib"
        ) from e
    import threading

    class RayBackend(ParallelBackendBase):
        """Batches of calls run as remote tasks instead of local forks."""

        supports_timeout = True
        supports_retrieve_callback = False

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 in Parallel has no meaning")
            if n_jobs is None:
                return 1
            if n_jobs < 0:
                return max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
            return n_jobs

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **backend_kwargs):
            self.parallel = parallel
            self._run = ray_tpu.remote(_call)
            self._pending: dict = {}   # ref -> (future, callback)
            self._cv = threading.Condition()
            self._stop = False
            self._drainer = None
            return self.effective_n_jobs(n_jobs)

        def _drain_loop(self):
            """Single thread firing completion callbacks — joblib dispatches
            further batches from them. One thread regardless of how many
            batches are in flight (errors surface via retrieve_result on
            the main thread, not here)."""
            while True:
                with self._cv:
                    while not self._pending and not self._stop:
                        self._cv.wait()
                    if self._stop and not self._pending:
                        return
                    refs = list(self._pending)
                ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.5)
                for ref in ready:
                    with self._cv:
                        fut, callback = self._pending.pop(ref)
                    if callback is not None:
                        callback(fut)

        def submit(self, func, callback=None):
            ref = self._run.remote(func)
            fut = _RayFuture(ref)
            with self._cv:
                self._pending[ref] = (fut, callback)
                if self._drainer is None:
                    self._drainer = threading.Thread(target=self._drain_loop,
                                                     daemon=True)
                    self._drainer.start()
                self._cv.notify()
            return fut

        # Legacy name some joblib versions still call.
        def apply_async(self, func, callback=None):
            return self.submit(func, callback)

        def terminate(self):
            with self._cv:
                self._stop = True
                self._cv.notify()

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    register_parallel_backend("ray", RayBackend)
