"""Distributed tracing: spans around task/actor submission and execution,
W3C trace context propagated inside the TaskSpec.

Parity: reference python/ray/util/tracing/tracing_helper.py:34-181
(_tracing_task_invocation wraps submission, _inject_tracing_into_function
wraps execution; context rides in the TaskSpec).

Two layers:
- Built-in propagation (always available): W3C `traceparent` strings are
  generated/parsed internally and carried in TaskSpec.trace_ctx, so a task
  anywhere in the cluster can see the root trace id.
- OpenTelemetry export (optional): when an OTel SDK TracerProvider is
  passed to `setup_tracing` (or installed globally), real spans are
  emitted through it as well — the standard API/SDK split: this library
  speaks the API, the application provides the SDK/exporter.

Enable with `setup_tracing()` in the driver; worker processes auto-enable
via the RAY_TPU_TRACING env var.
"""

from __future__ import annotations

import contextvars
import os
import secrets
from contextlib import contextmanager

_enabled = False
_otel_tracer = None

# (trace_id_hex32, span_id_hex16) of the active span in this task/process.
_current: contextvars.ContextVar[tuple[str, str] | None] = \
    contextvars.ContextVar("ray_tpu_trace", default=None)


def setup_tracing(tracer_provider=None) -> None:
    """Turn on tracing in this process. Optionally pass a configured
    opentelemetry SDK TracerProvider to also export real spans."""
    global _enabled, _otel_tracer
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"
    if tracer_provider is not None:
        from opentelemetry import trace

        trace.set_tracer_provider(tracer_provider)
        _otel_tracer = trace.get_tracer("ray_tpu")
    else:
        try:
            from opentelemetry import trace

            _otel_tracer = trace.get_tracer("ray_tpu")
        except ImportError:
            _otel_tracer = None


def maybe_setup_from_env() -> None:
    if not _enabled and os.environ.get("RAY_TPU_TRACING") == "1":
        setup_tracing()


def enabled() -> bool:
    return _enabled


def _format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def _parse_traceparent(tp: str) -> tuple[str, str] | None:
    parts = tp.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


def current_traceparent() -> str:
    """W3C traceparent for the active context ('' when none). Prefers a
    live OTel span (SDK installed), else the built-in context."""
    if not _enabled:
        return ""
    try:
        from opentelemetry import trace

        ctx = trace.get_current_span().get_span_context()
        if ctx.trace_id:
            return _format_traceparent(format(ctx.trace_id, "032x"),
                                       format(ctx.span_id, "016x"))
    except ImportError:
        pass
    cur = _current.get()
    if cur is None:
        return ""
    return _format_traceparent(*cur)


@contextmanager
def _span(name: str, task_id: str, parent_tp: str | None):
    """Built-in span: continue the parent's trace (or the ambient one, or
    start fresh), plus an OTel span when an SDK is wired up."""
    parent = _parse_traceparent(parent_tp) if parent_tp else None
    if parent is None:
        ambient = _parse_traceparent(current_traceparent() or "")
        parent = ambient
    trace_id = parent[0] if parent else secrets.token_hex(16)
    span_id = secrets.token_hex(8)
    token = _current.set((trace_id, span_id))
    otel_cm = None
    try:
        if _otel_tracer is not None:
            from opentelemetry import trace as otrace

            ctx = None
            if parent:
                from opentelemetry.trace import (
                    NonRecordingSpan,
                    SpanContext,
                    TraceFlags,
                )
                from opentelemetry.trace.propagation import set_span_in_context

                ctx = set_span_in_context(NonRecordingSpan(SpanContext(
                    trace_id=int(parent[0], 16), span_id=int(parent[1], 16),
                    is_remote=True, trace_flags=TraceFlags(1))))
            otel_cm = _otel_tracer.start_as_current_span(
                name, context=ctx, attributes={"ray_tpu.task_id": task_id})
            otel_cm.__enter__()
        try:
            yield
        except BaseException:
            # Let the OTel span record the failure (status + exception
            # event) instead of exporting errored tasks as OK.
            if otel_cm is not None:
                import sys

                otel_cm.__exit__(*sys.exc_info())
                otel_cm = None
            raise
    finally:
        if otel_cm is not None:
            otel_cm.__exit__(None, None, None)
        _current.reset(token)


@contextmanager
def submit_span(name: str, task_id: str):
    """Span around client-side submission; yields the traceparent to embed
    in the TaskSpec (reference: _tracing_task_invocation)."""
    if not _enabled:
        yield ""
        return
    with _span(f"{name} ray_tpu.remote", task_id, None):
        yield current_traceparent()


@contextmanager
def execute_span(name: str, task_id: str, traceparent: str):
    """Span around worker-side execution, parented to the submitter's span
    (reference: _inject_tracing_into_function). A spec carrying trace
    context activates tracing here even if this worker predates the
    driver's setup_tracing() (workers inherit env only at spawn time)."""
    if traceparent and not _enabled:
        maybe_setup_from_env()
        if not _enabled:
            setup_tracing()
    if not _enabled:
        yield
        return
    with _span(f"{name} ray_tpu.execute", task_id, traceparent or None):
        yield
