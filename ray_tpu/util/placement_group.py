"""Placement groups: gang resource reservation across nodes.

Parity: reference python/ray/util/placement_group.py (strategies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD at :16-19, placement_group() at :146).
TPU-first addition: the STRICT_ICI strategy places all bundles on nodes of
one ICI-connected TPU slice (nodes sharing a `tpu-slice` label) — the
gang-lease unit for multi-host SPMD programs (see SURVEY.md §7 stage 3).
"""

from __future__ import annotations

import time

from ray_tpu import exceptions as exc
from ray_tpu._private.api_internal import get_core_worker
from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "STRICT_ICI")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_specs(self) -> list[dict]:
        return self.bundles

    def ready(self):
        """Returns an ObjectRef that resolves once the PG is scheduled.

        The reference submits a probe task (bundle_reservation_check_func)
        into bundle 0 (python/ray/util/placement_group.py ready()); here
        the promise is settled straight off the GCS PG pubsub channel —
        CREATED is only published after every bundle's 2PC commit, so it
        validates the same thing without leasing (and on a fresh cluster,
        SPAWNING) one worker per placement group."""
        return get_core_worker().pg_ready_promise(self.id.hex())

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the PG is scheduled (reference:
        PlacementGroup.wait(timeout_seconds))."""
        cw = get_core_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            resp = cw._run(cw.gcs.call("GetPlacementGroup", {"pg_id": self.id.hex()}))
            if resp.get("found") and resp["state"] == "CREATED":
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.02)

    def bundle_node_ids(self) -> list[str]:
        cw = get_core_worker()
        resp = cw._run(cw.gcs.call("GetPlacementGroup", {"pg_id": self.id.hex()}))
        if not resp.get("found"):
            raise exc.PlacementGroupSchedulingError("placement group not found")
        return [b["node_id"] for b in resp["bundles"]]


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or not all(isinstance(b, dict) and b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    cw = get_core_worker()
    pg_id = PlacementGroupID.from_random()
    resp = cw._run(cw.gcs.call("CreatePlacementGroup", {
        "pg_id": pg_id.hex(), "bundles": bundles, "strategy": strategy,
        "name": name, "job_id": cw.job_id}))
    if not resp.get("ok"):
        raise exc.PlacementGroupSchedulingError("placement group creation failed")
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    cw = get_core_worker()
    cw._run(cw.gcs.call("RemovePlacementGroup", {"pg_id": pg.id.hex()}))


def placement_group_table() -> list[dict]:
    cw = get_core_worker()
    return cw._run(cw.gcs.call("ListPlacementGroups", {}))["placement_groups"]
