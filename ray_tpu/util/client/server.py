"""Client proxy server: hosts remote drivers over the msgpack RPC protocol.

Re-design of the reference Ray Client server (reference:
python/ray/util/client/server/server.py + proxier.py — a gRPC proxy that
runs a server-side driver per remote client). Here the proxy lives inside
any process that has called ray_tpu.init() (typically the head node); each
client connection gets a Session that tracks the refs and actors created
on the client's behalf, released on disconnect.

Two value codecs per request:
  "pickle"  — Python clients: cloudpickled blobs, refs swapped via
              common.ServerPickler markers.
  "msgpack" — cross-language clients (the C++ frontend, cpp/): values are
              plain msgpack structures carried inside the RPC payload
              (reference: msgpack cross-language serialization path).
"""

from __future__ import annotations

import asyncio
import io
import importlib
import logging
import threading
import uuid

from ray_tpu._private import rpc
from ray_tpu.util.client import common

logger = logging.getLogger(__name__)


def _resolve_qualified(name: str):
    """Resolve "module:attr" or "module.attr" to a Python object
    (reference: cross-language function descriptors,
    python/ray/cross_language.py)."""
    if ":" in name:
        mod_name, attr = name.split(":", 1)
    else:
        mod_name, _, attr = name.rpartition(".")
        if not mod_name:
            raise ValueError(f"qualified name required, got {name!r}")
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


class Session:
    """Per-connection server state: pinned refs + actors owned by one client."""

    def __init__(self, server: "ClientServer", conn: rpc.Connection):
        self.server = server
        self.conn = conn
        self.id = uuid.uuid4().hex[:12]
        self.refs: dict[str, object] = {}      # hex -> ObjectRef (pins it)
        self.actors: dict[str, object] = {}    # hex -> ActorHandle
        self.detached: set[str] = set()        # actor hexes to keep on close
        self.func_cache: dict[str, object] = {}  # key -> fn/class
        self.streams: dict[str, object] = {}   # stream id -> live generator

    def pin_ref(self, ref) -> None:
        self.refs.setdefault(ref.hex(), ref)

    def resolve_ref(self, ref_hex: str):
        ref = self.refs.get(ref_hex)
        if ref is None:
            raise KeyError(f"client session {self.id}: unknown ref {ref_hex[:16]}")
        return ref

    def resolve_actor(self, actor_hex: str, class_name: str):
        handle = self.actors.get(actor_hex)
        if handle is None:
            from ray_tpu._private.api_internal import ActorHandle
            from ray_tpu._private.ids import ActorID

            handle = ActorHandle(ActorID.from_hex(actor_hex), class_name)
            self.actors[actor_hex] = handle
        return handle

    def close(self) -> None:
        import ray_tpu

        for gen in list(self.streams.values()):
            try:
                gen.close()
            except Exception:
                pass
        self.streams.clear()
        self.refs.clear()
        for hex_id, handle in self.actors.items():
            if hex_id in self.detached:
                continue
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        self.actors.clear()


class ClientServer:
    """Serves remote clients against this process's driver CoreWorker."""

    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        from ray_tpu._private.api_internal import get_core_worker

        self.cw = get_core_worker()  # raises if init() not called
        self.requested_host, self.requested_port = host, port
        self.host = self.port = None
        self._sessions: dict[int, Session] = {}
        self._server = rpc.RpcServer(self._handlers(), name="client-server",
                                     on_connect=self._on_connect)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(
            target=self._run, name="ray-tpu-client-server", daemon=True)
        self._thread.start()
        self._started.wait(10.0)
        if self.port is None:
            raise RuntimeError("client server failed to start")
        return self.host, self.port

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def go():
            self.host, self.port = await self._server.start(
                self.requested_host, self.requested_port)
            self._started.set()

        self._loop.run_until_complete(go())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def stop(self):
        if self._loop is None:
            return

        async def down():
            for s in list(self._sessions.values()):
                s.close()
            self._sessions.clear()
            await self._server.stop()
            self._loop.stop()

        asyncio.run_coroutine_threadsafe(down(), self._loop)
        if self._thread is not None:
            self._thread.join(5.0)

    def _on_connect(self, conn: rpc.Connection):
        session = Session(self, conn)
        self._sessions[id(conn)] = session

        def gone():
            s = self._sessions.pop(id(conn), None)
            if s is not None:
                # Session teardown calls into the cluster; keep it off the
                # RPC loop.
                threading.Thread(target=s.close, daemon=True).start()

        conn.on_close(gone)

    def _session(self, conn) -> Session:
        s = self._sessions.get(id(conn))
        if s is None:
            raise rpc.RpcError("no session for connection")
        return s

    # -- request plumbing --------------------------------------------------

    def _handlers(self):
        return {
            "ClientPing": self._ping,
            "ClientPut": self._wrap(self._put),
            "ClientGet": self._wrap(self._get),
            "ClientWait": self._wrap(self._wait),
            "ClientRegisterFunction": self._wrap(self._register_function),
            "ClientTask": self._wrap(self._task),
            "ClientActorCreate": self._wrap(self._actor_create),
            "ClientActorCall": self._wrap(self._actor_call),
            "ClientKill": self._wrap(self._kill),
            "ClientCancel": self._wrap(self._cancel),
            "ClientRelease": self._wrap(self._release),
            "ClientGetActor": self._wrap(self._get_actor),
            "ClientClusterInfo": self._wrap(self._cluster_info),
            "ClientGcsCall": self._wrap(self._gcs_call),
            "ClientStreamClose": self._wrap(self._stream_close),
        }

    async def _ping(self, conn, payload):
        return {"ok": True, "session": self._session(conn).id}

    def _wrap(self, fn):
        async def handler(conn, payload):
            session = self._session(conn)
            token = common.current_session.set(session)
            try:
                # Blocking cluster calls run off the RPC loop so one slow
                # client get() cannot stall every session.
                return await asyncio.to_thread(fn, session, payload or {})
            finally:
                common.current_session.reset(token)
        return handler

    # -- value codecs ------------------------------------------------------

    def _load_args(self, session, payload):
        codec = payload.get("codec", "pickle")
        if codec == "msgpack":
            resolve = lambda v: self._resolve_markers(session, v)
            return (tuple(resolve(a) for a in (payload.get("margs") or [])),
                    {k: resolve(v)
                     for k, v in (payload.get("mkwargs") or {}).items()})
        blob = payload["args"]
        args, kwargs = common.loads(blob)
        return args, kwargs

    def _resolve_markers(self, session, value):
        """Swap {"__client_ref__": hex} markers in msgpack args for the
        session's real ObjectRefs (cross-language ref passing)."""
        if isinstance(value, dict):
            if set(value.keys()) == {"__client_ref__"}:
                return session.resolve_ref(value["__client_ref__"])
            return {k: self._resolve_markers(session, v)
                    for k, v in value.items()}
        if isinstance(value, (list, tuple)):
            return [self._resolve_markers(session, v) for v in value]
        return value

    def _dump_value(self, session, value, codec: str):
        if codec == "msgpack":
            return value  # carried natively in the RPC frame
        return common.server_dumps(value, session)

    def _new_refs(self, session, refs) -> list[str]:
        single = not isinstance(refs, list)
        if single:
            refs = [refs]
        out = []
        for r in refs:
            session.pin_ref(r)
            out.append(r.hex())
        return out

    # -- operations --------------------------------------------------------

    def _put(self, session, payload):
        import ray_tpu

        if payload.get("codec") == "msgpack":
            value = payload.get("value")
        else:
            value = common.loads(payload["data"])
        ref = ray_tpu.put(value)
        return {"refs": self._new_refs(session, ref)}

    def _get(self, session, payload):
        import ray_tpu

        codec = payload.get("codec", "pickle")
        refs = [session.resolve_ref(h) for h in payload["refs"]]
        try:
            values = ray_tpu.get(refs, timeout=payload.get("timeout"))
        except Exception as e:  # ship the exception for client-side re-raise
            if codec == "msgpack":
                return {"ok": False, "error_str": f"{type(e).__name__}: {e}"}
            return {"ok": False, "error": common.server_dumps(e, session)}
        return {"ok": True,
                "values": [self._dump_value(session, v, codec) for v in values]}

    def _wait(self, session, payload):
        import ray_tpu

        refs = [session.resolve_ref(h) for h in payload["refs"]]
        ready, not_ready = ray_tpu.wait(
            refs, num_returns=payload.get("num_returns", 1),
            timeout=payload.get("timeout"))
        return {"ready": [r.hex() for r in ready],
                "not_ready": [r.hex() for r in not_ready]}

    def _register_function(self, session, payload):
        fn = common.loads(payload["fn"])
        key = uuid.uuid4().hex
        session.func_cache[key] = fn
        return {"key": key}

    def _resolve_callable(self, session, payload):
        from ray_tpu._private.api_internal import (ActorClass, RemoteFunction,
                                                   make_remote)

        if payload.get("name"):
            obj = _resolve_qualified(payload["name"])
        else:
            obj = session.func_cache[payload["key"]]
        if payload.get("opts_pkl") is not None:
            opts = common.loads(payload["opts_pkl"])
        else:
            opts = payload.get("opts") or {}
        if isinstance(obj, (RemoteFunction, ActorClass)):
            return obj.options(**opts) if opts else obj
        return make_remote(obj, opts)

    def _start_stream(self, session, stream_id: str, gen) -> None:
        """Pump a server-side ObjectRefGenerator to the remote client as
        ClientStreamItem/End/Error notifies (reference: the gRPC client
        server streams generator returns back to ray:// drivers). The
        client pre-allocated `stream_id` and registered its queue before
        sending the request, so no yield can outrun the plumbing."""
        session.streams[stream_id] = gen
        conn = session.conn

        def notify(method, payload):
            try:
                fut = asyncio.run_coroutine_threadsafe(
                    conn.notify(method, payload), self._loop)
                fut.result(30.0)
                return True
            except Exception:
                return False

        def pump():
            try:
                for ref in gen:
                    session.pin_ref(ref)
                    if not notify("ClientStreamItem",
                                  {"stream": stream_id, "ref": ref.hex()}):
                        gen.close()  # client gone: free unconsumed yields
                        return
                notify("ClientStreamEnd", {"stream": stream_id})
            except Exception as e:
                notify("ClientStreamError",
                       {"stream": stream_id,
                        "error": common.server_dumps(e, session)})
            finally:
                session.streams.pop(stream_id, None)

        threading.Thread(target=pump, daemon=True,
                         name=f"client-stream-{stream_id[:8]}").start()

    def _task(self, session, payload):
        from ray_tpu._private.api_internal import ObjectRefGenerator

        rf = self._resolve_callable(session, payload)
        args, kwargs = self._load_args(session, payload)
        refs = rf.remote(*args, **kwargs)
        if isinstance(refs, ObjectRefGenerator):
            self._start_stream(session, payload["stream"], refs)
            return {"stream": payload["stream"]}
        return {"refs": self._new_refs(session, refs)}

    def _stream_close(self, session, payload):
        gen = session.streams.pop(payload["stream"], None)
        if gen is not None:
            gen.close()  # frees buffered + later yields; wakes the pump
        return {}

    def _actor_create(self, session, payload):
        from ray_tpu._private.api_internal import ActorClass

        ac = self._resolve_callable(session, payload)
        if not isinstance(ac, ActorClass):
            raise TypeError("ClientActorCreate requires a class")
        args, kwargs = self._load_args(session, payload)
        handle = ac.remote(*args, **kwargs)
        session.actors[handle._id_hex] = handle
        if payload.get("detached"):
            session.detached.add(handle._id_hex)
        return {"actor_id": handle._id_hex, "class_name": handle._class_name}

    def _actor_call(self, session, payload):
        from ray_tpu._private.api_internal import ObjectRefGenerator

        handle = session.resolve_actor(payload["actor"],
                                       payload.get("class_name", "Actor"))
        method = getattr(handle, payload["method"])
        if payload.get("num_returns", 1) != 1:
            method = method.options(num_returns=payload["num_returns"])
        args, kwargs = self._load_args(session, payload)
        refs = method.remote(*args, **kwargs)
        if isinstance(refs, ObjectRefGenerator):
            self._start_stream(session, payload["stream"], refs)
            return {"stream": payload["stream"]}
        return {"refs": self._new_refs(session, refs)}

    def _kill(self, session, payload):
        import ray_tpu

        handle = session.resolve_actor(payload["actor"],
                                       payload.get("class_name", "Actor"))
        ray_tpu.kill(handle, no_restart=payload.get("no_restart", True))
        session.actors.pop(payload["actor"], None)
        return {}

    def _cancel(self, session, payload):
        import ray_tpu

        ref = session.resolve_ref(payload["ref"])
        ray_tpu.cancel(ref, force=payload.get("force", False))
        return {}

    def _release(self, session, payload):
        for h in payload.get("refs", []):
            session.refs.pop(h, None)
        return {}

    def _get_actor(self, session, payload):
        import ray_tpu

        handle = ray_tpu.get_actor(payload["name"],
                                   namespace=payload.get("namespace"))
        session.actors[handle._id_hex] = handle
        session.detached.add(handle._id_hex)  # named actors are not ours
        return {"actor_id": handle._id_hex, "class_name": handle._class_name}

    def _cluster_info(self, session, payload):
        import ray_tpu

        return {"nodes": ray_tpu.nodes(),
                "resources": ray_tpu.cluster_resources(),
                "available": ray_tpu.available_resources()}

    def _gcs_call(self, session, payload):
        cw = self.cw
        return cw._run(cw.gcs.call(payload["method"], payload.get("payload")))


def serve(host: str = "0.0.0.0", port: int = 10001) -> ClientServer:
    """Start a client proxy in this (already-initialized) driver process."""
    server = ClientServer(host, port)
    server.start()
    return server
