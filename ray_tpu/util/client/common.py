"""Shared wire types for the client protocol (reference:
python/ray/util/client/common.py — ClientObjectRef/ClientActorRef).

Cross-process pickling: values crossing the client<->server boundary may
contain ObjectRefs / ActorHandles. Each side swaps its own ref types for
resolvable markers before pickling, so the other side reconstructs the
right kind of handle:

  server -> client: real ObjectRef  -> ClientObjectRef (registered in the
                    session ref table so the server keeps it alive)
  client -> server: ClientObjectRef -> the session's real ObjectRef
                    (resolved through a contextvar set per request)
"""

from __future__ import annotations

import contextvars
import io
import pickle

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None

# Set by the server around every request dispatch so client-ref markers
# deserialize to that session's real refs.
current_session: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_client_session", default=None)

# Set in the client process (the ClientContext) so server-ref markers
# deserialize to ClientObjectRef bound to that connection.
current_client: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_client_context", default=None)


class ClientObjectRef:
    """Client-side handle to an object owned by the server-side driver."""

    __slots__ = ("hex", "_ctx", "__weakref__")

    def __init__(self, ref_hex: str, ctx=None):
        self.hex = ref_hex
        self._ctx = ctx

    def binary(self) -> bytes:
        return bytes.fromhex(self.hex)

    def __hash__(self):
        return hash(self.hex)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and other.hex == self.hex

    def __repr__(self):
        return f"ClientObjectRef({self.hex[:16]})"

    def __reduce__(self):
        # Pickled client->server inside task args: resolve to the real ref.
        return (_resolve_ref_on_server, (self.hex,))

    def __del__(self):
        ctx = self._ctx
        if ctx is not None:
            try:
                ctx._release(self.hex)
            except Exception:
                pass


class ClientActorHandle:
    """Client-side handle to an actor created through the proxy."""

    def __init__(self, actor_hex: str, class_name: str, ctx=None):
        self._actor_hex = actor_hex
        self._class_name = class_name
        self._ctx = ctx

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientActorMethod(self, name, 1)

    def __reduce__(self):
        return (_resolve_actor_on_server, (self._actor_hex, self._class_name))

    def __repr__(self):
        return f"ClientActorHandle({self._class_name}, {self._actor_hex[:12]})"


class _ClientActorMethod:
    def __init__(self, handle: ClientActorHandle, name: str, num_returns: int):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, **opts):
        return _ClientActorMethod(self._handle, self._name,
                                  opts.get("num_returns", self._num_returns))

    def remote(self, *args, **kwargs):
        ctx = self._handle._ctx
        if ctx is None:
            raise RuntimeError("actor handle is not bound to a client")
        return ctx._actor_call(self._handle._actor_hex, self._name,
                               args, kwargs, self._num_returns)


def _active_client():
    """The client context for this process: the contextvar when set, else
    the process-global one (unpickling can happen on any thread, and
    contextvars don't cross threads)."""
    ctx = current_client.get()
    if ctx is not None:
        return ctx
    try:
        import ray_tpu

        return ray_tpu._client_ctx
    except Exception:
        return None


def _resolve_ref_on_server(ref_hex: str):
    session = current_session.get()
    if session is None:
        # Unpickled in a plain client process (e.g. a round trip): rebuild
        # a client ref bound to the active context.
        return ClientObjectRef(ref_hex, _active_client())
    return session.resolve_ref(ref_hex)


def _resolve_actor_on_server(actor_hex: str, class_name: str):
    session = current_session.get()
    if session is None:
        return ClientActorHandle(actor_hex, class_name, _active_client())
    return session.resolve_actor(actor_hex, class_name)


def _rebuild_client_ref(ref_hex: str):
    """Server->client marker: becomes a ClientObjectRef on the client."""
    session = current_session.get()
    if session is not None:  # value bounced back to the server
        return session.resolve_ref(ref_hex)
    return ClientObjectRef(ref_hex, _active_client())


def _rebuild_client_actor(actor_hex: str, class_name: str):
    session = current_session.get()
    if session is not None:
        return session.resolve_actor(actor_hex, class_name)
    return ClientActorHandle(actor_hex, class_name, _active_client())


class ServerPickler(pickle.Pickler):
    """Server-side pickler: swaps real refs for client markers, pinning
    each emitted ref in the session table so it survives until the client
    releases it."""

    def __init__(self, file, session):
        super().__init__(file, protocol=5)
        self.session = session

    def reducer_override(self, obj):
        from ray_tpu._private.api_internal import ActorHandle, ObjectRef

        if isinstance(obj, ObjectRef):
            self.session.pin_ref(obj)
            return (_rebuild_client_ref, (obj.hex(),))
        if isinstance(obj, ActorHandle):
            return (_rebuild_client_actor, (obj._id_hex, obj._class_name))
        return NotImplemented


def server_dumps(value, session) -> bytes:
    buf = io.BytesIO()
    ServerPickler(buf, session).dump(value)
    return buf.getvalue()


def client_dumps(value) -> bytes:
    """Client-side serialization; cloudpickle so lambdas/closures work in
    task args the same as on a cluster driver."""
    if cloudpickle is not None:
        return cloudpickle.dumps(value, protocol=5)
    return pickle.dumps(value, protocol=5)


def loads(data: bytes):
    return pickle.loads(data)
