"""Client-side driver: talks to a ClientServer over msgpack RPC.

Re-design of the reference Ray Client worker (reference:
python/ray/util/client/worker.py — the `ray://` driver that proxies the
public API over gRPC). Connect with
ray_tpu.init(address="client://host:port"); the public API then routes
through the ClientContext here instead of a local CoreWorker.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import threading
import uuid
from typing import Any, Sequence

from ray_tpu import exceptions
from ray_tpu._private import rpc
from ray_tpu.util.client import common
from ray_tpu.util.client.common import ClientActorHandle, ClientObjectRef

_OP_TIMEOUT = 60.0


class ClientObjectRefGenerator:
    """Client-side streaming generator: yields ClientObjectRefs as the
    in-cluster generator produces them, pushed by the proxy as
    ClientStreamItem notifies (reference: ray:// streaming generator
    passthrough)."""

    def __init__(self, ctx: "ClientContext", stream_id: str,
                 q: "_queue.Queue"):
        self._ctx = ctx
        self._id = stream_id
        self._q = q
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> ClientObjectRef:
        if self._done:
            raise StopIteration
        kind, val = self._q.get()
        if kind == "item":
            return ClientObjectRef(val, self._ctx)
        self._done = True
        self._ctx._streams.pop(self._id, None)
        if kind == "end":
            raise StopIteration
        raise common.loads(val)  # server-shipped exception

    def completed(self) -> bool:
        return self._done

    def close(self) -> None:
        """Stop the stream: the proxy closes the in-cluster generator
        (freeing unconsumed yields) and buffered refs are released."""
        if self._done:
            return
        self._done = True
        self._ctx._streams.pop(self._id, None)
        try:
            self._ctx._rpc("ClientStreamClose", {"stream": self._id})
        except Exception:
            pass
        while True:
            try:
                kind, val = self._q.get_nowait()
            except _queue.Empty:
                return
            if kind == "item":
                self._ctx._release(val)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn, opts: dict):
        self._ctx = ctx
        self._fn = fn
        self._opts = opts
        self._key: str | None = None

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        rf = ClientRemoteFunction(self._ctx, self._fn, merged)
        rf._key = self._key
        return rf

    def remote(self, *args, **kwargs):
        if self._key is None:
            self._key = self._ctx._register_function(self._fn)
        return self._ctx._task(self._key, args, kwargs, self._opts)

    def __call__(self, *a, **k):
        raise TypeError("remote function cannot be called directly; use .remote()")


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", cls, opts: dict):
        self._ctx = ctx
        self._cls = cls
        self._opts = opts
        self._key: str | None = None

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        ac = ClientActorClass(self._ctx, self._cls, merged)
        ac._key = self._key
        return ac

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        if self._key is None:
            self._key = self._ctx._register_function(self._cls)
        return self._ctx._actor_create(self._key, args, kwargs, self._opts)

    def __call__(self, *a, **k):
        raise TypeError("actor class cannot be instantiated directly; use .remote()")


class ClientContext:
    """One connection to a client proxy; owns a background RPC loop."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0):
        self.host, self.port = host, port
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="ray-tpu-client", daemon=True)
        self._thread.start()
        self._streams: dict[str, _queue.Queue] = {}
        # dial, not a session: the proxy keeps per-connection state, so
        # a lost socket means this client session is over (the _rpc
        # ConnectionLost path surfaces that to the caller).
        self._conn: rpc.Connection = self._call_soon(
            rpc.dial(host, port, name="client",
                     handlers={
                         "ClientStreamItem": self._on_stream_ev,
                         "ClientStreamEnd": self._on_stream_ev,
                         "ClientStreamError": self._on_stream_ev,
                     },
                     timeout=connect_timeout),
            timeout=connect_timeout + 5.0)
        self._token = common.current_client.set(self)
        self._closed = False
        self.session_id = self._rpc("ClientPing", {})["session"]

    # -- plumbing ----------------------------------------------------------

    def _call_soon(self, coro, timeout=_OP_TIMEOUT):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout)

    def _rpc(self, method: str, payload, timeout=_OP_TIMEOUT):
        """timeout=None blocks indefinitely (get()/wait() semantics match
        the local driver path)."""
        if self._closed:
            raise exceptions.RayTpuError("client connection is closed")
        try:
            return self._call_soon(
                self._conn.call(method, payload, timeout=timeout),
                timeout=timeout + 5.0 if timeout is not None else None)
        except rpc.ConnectionLost:
            self._closed = True
            raise exceptions.RayTpuError(
                f"lost connection to client server {self.host}:{self.port}")

    async def _on_stream_ev(self, conn, payload):
        """Stream notifies from the proxy (runs on the client loop)."""
        q = self._streams.get(payload["stream"])
        if q is None:
            # Stream already closed client-side: free the orphan item.
            if "ref" in payload:
                self._release(payload["ref"])
            return
        if "ref" in payload:
            q.put(("item", payload["ref"]))
        elif "error" in payload:
            q.put(("error", payload["error"]))
        else:
            q.put(("end", None))

    def _release(self, ref_hex: str):
        if self._closed or not self._loop.is_running():
            return

        async def send():
            try:
                await self._conn.notify("ClientRelease", {"refs": [ref_hex]})
            except Exception:
                pass
        try:
            asyncio.run_coroutine_threadsafe(send(), self._loop)
        except RuntimeError:
            pass

    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            common.current_client.reset(self._token)
        except (ValueError, LookupError):
            # close() may run on a different thread than __init__ set the
            # contextvar on; the process-global fallback in common.py makes
            # the var cosmetic, so a cross-thread reset is safely skipped.
            pass
        try:
            self._call_soon(self._conn.close(), timeout=5.0)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(5.0)

    def _wire_refs(self, payload) -> list[ClientObjectRef]:
        return [ClientObjectRef(h, self) for h in payload["refs"]]

    # -- API surface -------------------------------------------------------

    def remote(self, obj, opts: dict):
        if isinstance(obj, type):
            return ClientActorClass(self, obj, opts)
        if callable(obj):
            return ClientRemoteFunction(self, obj, opts)
        raise TypeError("@ray_tpu.remote requires a function or class")

    def put(self, value: Any) -> ClientObjectRef:
        if isinstance(value, ClientObjectRef):
            raise TypeError("ray_tpu.put() of an ObjectRef is not allowed")
        resp = self._rpc("ClientPut", {"data": common.client_dumps(value)})
        return self._wire_refs(resp)[0]

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        refs = list(refs)
        for r in refs:
            if not isinstance(r, ClientObjectRef):
                raise TypeError(f"ray_tpu.get() takes ObjectRefs, got {type(r)}")
        resp = self._rpc("ClientGet",
                         {"refs": [r.hex for r in refs], "timeout": timeout},
                         timeout=None if timeout is None else timeout + 30.0)
        if not resp["ok"]:
            raise common.loads(resp["error"])
        values = [common.loads(v) for v in resp["values"]]
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns=1,
             timeout=None):
        refs = list(refs)
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        resp = self._rpc("ClientWait", {
            "refs": [r.hex for r in refs], "num_returns": num_returns,
            "timeout": timeout},
            timeout=None if timeout is None else timeout + 30.0)
        by_hex = {r.hex: r for r in refs}
        return ([by_hex[h] for h in resp["ready"]],
                [by_hex[h] for h in resp["not_ready"]])

    def _register_function(self, fn) -> str:
        return self._rpc("ClientRegisterFunction",
                         {"fn": common.client_dumps(fn)})["key"]

    def _begin_stream(self):
        """Pre-allocate a stream id + queue BEFORE the request goes out:
        yields may start arriving before the RPC reply."""
        stream_id = uuid.uuid4().hex[:16]
        q: _queue.Queue = _queue.Queue()
        self._streams[stream_id] = q
        return stream_id, q

    def _task(self, key: str, args, kwargs, opts):
        streaming = opts.get("num_returns") in ("streaming", "dynamic")
        req = {"key": key, "args": common.client_dumps((args, kwargs)),
               "opts_pkl": common.client_dumps(opts)}
        if streaming:
            stream_id, q = self._begin_stream()
            req["stream"] = stream_id
            try:
                self._rpc("ClientTask", req)
            except Exception:
                self._streams.pop(stream_id, None)
                raise
            return ClientObjectRefGenerator(self, stream_id, q)
        resp = self._rpc("ClientTask", req)
        refs = self._wire_refs(resp)
        if opts.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def _actor_create(self, key: str, args, kwargs, opts) -> ClientActorHandle:
        resp = self._rpc("ClientActorCreate", {
            "key": key, "args": common.client_dumps((args, kwargs)),
            "opts_pkl": common.client_dumps(opts),
            "detached": opts.get("lifetime") == "detached"})
        return ClientActorHandle(resp["actor_id"], resp["class_name"], self)

    def _actor_call(self, actor_hex: str, method: str, args, kwargs,
                    num_returns):
        req = {"actor": actor_hex, "method": method,
               "args": common.client_dumps((args, kwargs)),
               "num_returns": num_returns}
        if num_returns in ("streaming", "dynamic"):
            stream_id, q = self._begin_stream()
            req["stream"] = stream_id
            try:
                self._rpc("ClientActorCall", req)
            except Exception:
                self._streams.pop(stream_id, None)
                raise
            return ClientObjectRefGenerator(self, stream_id, q)
        resp = self._rpc("ClientActorCall", req)
        refs = self._wire_refs(resp)
        return refs[0] if num_returns == 1 else refs

    def kill(self, actor: ClientActorHandle, *, no_restart: bool = True):
        if not isinstance(actor, ClientActorHandle):
            raise TypeError("ray_tpu.kill() takes an ActorHandle")
        self._rpc("ClientKill", {"actor": actor._actor_hex,
                                 "class_name": actor._class_name,
                                 "no_restart": no_restart})

    def cancel(self, ref: ClientObjectRef, *, force: bool = False):
        self._rpc("ClientCancel", {"ref": ref.hex, "force": force})

    def get_actor(self, name: str, namespace: str | None = None):
        resp = self._rpc("ClientGetActor",
                         {"name": name, "namespace": namespace})
        return ClientActorHandle(resp["actor_id"], resp["class_name"], self)

    def gcs_call(self, method: str, payload: dict | None = None) -> dict:
        """Proxy one GCS RPC through the client server (the transport
        behind the ray_tpu.util.state API in client mode: the proxy's
        in-cluster CoreWorker issues the call and relays the reply)."""
        return self._rpc("ClientGcsCall",
                         {"method": method, "payload": payload})

    def nodes(self) -> list[dict]:
        return self._rpc("ClientClusterInfo", {})["nodes"]

    def cluster_resources(self) -> dict:
        return self._rpc("ClientClusterInfo", {})["resources"]

    def available_resources(self) -> dict:
        return self._rpc("ClientClusterInfo", {})["available"]
