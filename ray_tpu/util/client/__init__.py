"""Remote-driver client mode (reference: python/ray/util/client/ — the
`ray://` proxy). Connect via ray_tpu.init(address="client://host:port");
serve with ray_tpu.util.client.server.serve() from any driver process."""

from ray_tpu.util.client.common import ClientActorHandle, ClientObjectRef
from ray_tpu.util.client.worker import ClientContext

__all__ = ["ClientActorHandle", "ClientContext", "ClientObjectRef"]
