"""Chrome-trace timeline from GCS task events.

Parity: reference `ray timeline` (scripts.py:2459) which dumps per-worker
profile events (core_worker/profile_event.cc → task_event_buffer.h) as a
chrome://tracing JSON. Here the GCS task-event table provides the full
lifecycle ladder: one "X" complete event per task execution on (node,
worker) rows, plus per-STAGE sub-spans (queue, lease negotiation,
dispatch, arg fetch) on dedicated "stage:<name>" rows so where a slow
task spent its pre-execution time is visible at a glance.
"""

from __future__ import annotations

import json

from ray_tpu._private.api_internal import get_core_worker

# Pre-execution ladder segments rendered as their own rows (everything
# up to and including RUNNING — one shared definition with the state
# API); the RUNNING→FINISHED span stays the per-worker execution row.
from ray_tpu.util.state import LIFECYCLE_STAGES

_STAGE_LADDER = LIFECYCLE_STAGES[:LIFECYCLE_STAGES.index("RUNNING") + 1]
_STAGE_NAMES = {"LEASE_REQUESTED": "queue", "LEASE_GRANTED": "lease",
                "DISPATCHED": "dispatch", "ARGS_FETCHED": "args_fetch",
                "RUNNING": "startup"}


def _stage_rows(task_stamps: dict[str, dict[str, dict]]) -> list[dict]:
    """Per-stage sub-spans: for each task, an 'X' between each pair of
    consecutive recorded ladder stamps, on a row per stage."""
    trace = []
    for tid, stamps in task_stamps.items():
        present = [s for s in _STAGE_LADDER if s in stamps]
        for frm, to in zip(present, present[1:]):
            e0, e1 = stamps[frm], stamps[to]
            trace.append({
                "name": e0.get("name", tid),
                "cat": "stage",
                "ph": "X",
                "ts": e0["ts"] * 1e6,
                "dur": max(0.0, (e1["ts"] - e0["ts"]) * 1e6),
                "pid": "lifecycle",
                "tid": f"stage:{_STAGE_NAMES[to]}",
                "args": {"task_id": tid, "from": frm, "to": to},
            })
    return trace


def build_trace_events(events: list[dict]) -> list[dict]:
    """Pair per-task state transitions into chrome trace 'X' events."""
    starts: dict[str, dict] = {}
    trace: list[dict] = []
    task_stamps: dict[str, dict[str, dict]] = {}
    for e in sorted(events, key=lambda e: e.get("ts", 0.0)):
        state = e.get("state")
        tid = e.get("task_id")
        if state in _STAGE_LADDER:
            task_stamps.setdefault(tid, {}).setdefault(state, e)
        if state == "RUNNING":
            starts[tid] = e
        elif state in ("FINISHED", "FAILED") and tid in starts:
            s = starts.pop(tid)
            trace.append({
                "name": s.get("name", tid),
                "cat": "task",
                "ph": "X",
                "ts": s["ts"] * 1e6,
                "dur": max(0.0, (e["ts"] - s["ts"]) * 1e6),
                "pid": s.get("node_id", "node")[:8],
                "tid": s.get("worker_id", "worker")[:8],
                "args": {"task_id": tid, "state": state,
                         "job_id": s.get("job_id", "")},
            })
    # Unfinished tasks appear as instant events.
    for tid, s in starts.items():
        trace.append({"name": s.get("name", tid), "cat": "task", "ph": "i",
                      "ts": s["ts"] * 1e6, "pid": s.get("node_id", "n")[:8],
                      "tid": s.get("worker_id", "w")[:8], "s": "t",
                      "args": {"task_id": tid, "state": "RUNNING"}})
    trace.extend(_stage_rows(task_stamps))
    return trace


def dump_timeline(path: str = "/tmp/ray_tpu_timeline.json",
                  limit: int = 100000) -> str:
    cw = get_core_worker()
    events = cw._run(cw.gcs.call("ListTaskEvents", {"limit": limit}))["events"]
    trace = build_trace_events(events)
    with open(path, "w") as f:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return path
