"""Chrome-trace timeline from GCS task events.

Parity: reference `ray timeline` (scripts.py:2459) which dumps per-worker
profile events (core_worker/profile_event.cc → task_event_buffer.h) as a
chrome://tracing JSON. Here the GCS task-event table provides the
RUNNING→FINISHED/FAILED pairs; rows are (node, worker), one "X" complete
event per task execution.
"""

from __future__ import annotations

import json

from ray_tpu._private.api_internal import get_core_worker


def build_trace_events(events: list[dict]) -> list[dict]:
    """Pair per-task state transitions into chrome trace 'X' events."""
    starts: dict[str, dict] = {}
    trace: list[dict] = []
    for e in sorted(events, key=lambda e: e.get("ts", 0.0)):
        state = e.get("state")
        tid = e.get("task_id")
        if state == "RUNNING":
            starts[tid] = e
        elif state in ("FINISHED", "FAILED") and tid in starts:
            s = starts.pop(tid)
            trace.append({
                "name": s.get("name", tid),
                "cat": "task",
                "ph": "X",
                "ts": s["ts"] * 1e6,
                "dur": max(0.0, (e["ts"] - s["ts"]) * 1e6),
                "pid": s.get("node_id", "node")[:8],
                "tid": s.get("worker_id", "worker")[:8],
                "args": {"task_id": tid, "state": state,
                         "job_id": s.get("job_id", "")},
            })
    # Unfinished tasks appear as instant events.
    for tid, s in starts.items():
        trace.append({"name": s.get("name", tid), "cat": "task", "ph": "i",
                      "ts": s["ts"] * 1e6, "pid": s.get("node_id", "n")[:8],
                      "tid": s.get("worker_id", "w")[:8], "s": "t",
                      "args": {"task_id": tid, "state": "RUNNING"}})
    return trace


def dump_timeline(path: str = "/tmp/ray_tpu_timeline.json",
                  limit: int = 100000) -> str:
    cw = get_core_worker()
    events = cw._run(cw.gcs.call("ListTaskEvents", {"limit": limit}))["events"]
    trace = build_trace_events(events)
    with open(path, "w") as f:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, f)
    return path
