"""Structured cluster events.

Parity: reference src/ray/util/event.h + dashboard/modules/event — daemons
emit typed, severity-tagged events (node death, actor failures, OOM kills,
spills) that operators can list after the fact. Here every process appends
JSON lines to its session `logs/events-<label>.jsonl`; `list_events()`
merges them time-ordered, and the CLI exposes `ray_tpu events`.
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_sink_path: str | None = None
_label = "unknown"

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")


def configure(session_dir: str, label: str) -> None:
    """Called by daemons/drivers at startup; events before configure()
    are dropped (no session to attribute them to)."""
    global _sink_path, _label
    logs = os.path.join(session_dir, "logs")
    os.makedirs(logs, exist_ok=True)
    _sink_path = os.path.join(logs, f"events-{label}.jsonl")
    _label = label


def record(severity: str, source: str, message: str, **fields) -> None:
    """Append one structured event (no-op before configure())."""
    if _sink_path is None:
        return
    if severity not in SEVERITIES:
        severity = "INFO"
    evt = {"ts": time.time(), "severity": severity, "source": source,
           "label": _label, "pid": os.getpid(), "message": message}
    if fields:
        evt["fields"] = fields
    line = json.dumps(evt, default=str)
    with _lock:
        try:
            with open(_sink_path, "a") as f:
                f.write(line + "\n")
        except OSError:
            pass


def list_events(session_dir: str, *, min_severity: str = "DEBUG",
                source: str | None = None, limit: int = 1000) -> list[dict]:
    """Merged, time-ordered events from every process of a session."""
    floor = SEVERITIES.index(min_severity)
    out: list[dict] = []
    logs = os.path.join(session_dir, "logs")
    try:
        names = [n for n in os.listdir(logs)
                 if n.startswith("events-") and n.endswith(".jsonl")]
    except OSError:
        return []
    for name in names:
        try:
            with open(os.path.join(logs, name)) as f:
                for line in f:
                    try:
                        evt = json.loads(line)
                    except ValueError:
                        continue
                    if SEVERITIES.index(evt.get("severity", "INFO")) < floor:
                        continue
                    if source and evt.get("source") != source:
                        continue
                    out.append(evt)
        except OSError:
            continue
    out.sort(key=lambda e: e.get("ts", 0))
    return out[-limit:]
