"""User-defined metrics: Counter / Gauge / Histogram.

Parity: reference python/ray/util/metrics.py:19. The reference exports via
OpenCensus → node metrics agent → Prometheus; here metrics publish to the
GCS KV (namespace "metrics") so any process (dashboard-lite, tests, a
Prometheus bridge) can scrape one place.
"""

from __future__ import annotations

import bisect
import json
import threading
import time

from ray_tpu._private.api_internal import core_worker_or_none

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}
_last_flush = [0.0]
_FLUSH_INTERVAL_S = 1.0


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict[str, str] = {}
        self._values: dict[tuple, float] = {}
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: dict[str, str] | None) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _flush_maybe(self):
        now = time.monotonic()
        if now - _last_flush[0] < _FLUSH_INTERVAL_S:
            return
        _last_flush[0] = now
        cw = core_worker_or_none()
        if cw is None or cw.gcs is None or cw.gcs.closed:
            return
        with _registry_lock:
            snapshot = {name: m.snapshot() for name, m in _registry.items()}
        try:
            cw._spawn(cw.gcs.call("KVPut", {
                "ns": "metrics",
                "key": f"worker:{cw.worker_id}".encode(),
                "value": json.dumps(snapshot).encode()}))
        except Exception:
            pass

    def snapshot(self) -> dict:
        return {"type": type(self).__name__, "description": self.description,
                "values": {json.dumps(k): v for k, v in self._values.items()}}


def flush_registry_now() -> bool:
    """Publish the CURRENT registry snapshot to the GCS synchronously.

    The per-set `_flush_maybe` path is throttled (1/s) and fire-and-
    forget — fine for user metrics, but a scrape that just updated a
    batch of gauges (export_pump_stats) must publish the complete batch
    BEFORE the exposition renders, or it serves the previous scrape's
    values. Returns False when no cluster is connected."""
    cw = core_worker_or_none()
    if cw is None or cw.gcs is None or cw.gcs.closed:
        return False
    with _registry_lock:
        snapshot = {name: m.snapshot() for name, m in _registry.items()}
    try:
        cw._run(cw.gcs.call("KVPut", {
            "ns": "metrics",
            "key": f"worker:{cw.worker_id}".encode(),
            "value": json.dumps(snapshot).encode()}, timeout=5))
        _last_flush[0] = time.monotonic()
        return True
    except Exception:
        return False


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: dict | None = None):
        key = self._tag_tuple(tags)
        self._values[key] = self._values.get(key, 0.0) + value
        self._flush_maybe()


class Gauge(Metric):
    def set(self, value: float, tags: dict | None = None):
        self._values[self._tag_tuple(tags)] = value
        self._flush_maybe()


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: list[float] | None = None,
                 tag_keys: tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, tags: dict | None = None):
        key = self._tag_tuple(tags)
        counts = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
        counts[bisect.bisect_left(self.boundaries, value)] += 1
        self._values[key] = value  # last observation
        self._sums[key] = self._sums.get(key, 0.0) + value
        self._flush_maybe()

    def snapshot(self) -> dict:
        base = super().snapshot()
        base["boundaries"] = self.boundaries
        base["counts"] = {json.dumps(k): v for k, v in self._counts.items()}
        base["sums"] = {json.dumps(k): v for k, v in self._sums.items()}
        return base


# ---------- elastic-training counters ----------
# Process-local running totals for the trainer's resize telemetry,
# exported as registry gauges (so /metrics and `ray_tpu status` surface
# them like any published metric). Gauges carry totals, counter-style:
# the trainer process is the single writer.

_train_elastic_lock = threading.Lock()
_train_elastic = {"shrink": 0, "grow": 0, "resizes_total": 0,
                  "steps_lost_total": 0, "fallbacks_total": 0}
_train_gauges: dict = {}


def _train_elastic_gauges() -> dict:
    with _train_elastic_lock:
        if not _train_gauges:
            _train_gauges["resizes"] = Gauge(
                "ray_tpu_train_resizes_total",
                "elastic gang resizes survived without a job restart",
                tag_keys=("direction",))
            _train_gauges["steps_lost"] = Gauge(
                "ray_tpu_train_steps_lost_total",
                "training steps lost across elastic resizes")
            _train_gauges["fallbacks"] = Gauge(
                "ray_tpu_train_elastic_fallbacks_total",
                "elastic resumes that fell back to checkpoint restart")
    return _train_gauges


def note_train_elastic(event: str, steps_lost: int = 0) -> None:
    """Record one elastic-training event ('shrink' / 'grow' /
    'fallback') and push the totals to the GCS so a scrape right after
    a resize sees it."""
    g = _train_elastic_gauges()
    with _train_elastic_lock:
        if event in ("shrink", "grow"):
            _train_elastic[event] += 1
            _train_elastic["resizes_total"] += 1
        elif event == "fallback":
            _train_elastic["fallbacks_total"] += 1
        _train_elastic["steps_lost_total"] += int(steps_lost)
        snap = dict(_train_elastic)
    g["resizes"].set(snap["shrink"], tags={"direction": "shrink"})
    g["resizes"].set(snap["grow"], tags={"direction": "grow"})
    g["steps_lost"].set(snap["steps_lost_total"])
    g["fallbacks"].set(snap["fallbacks_total"])
    flush_registry_now()


def train_elastic_snapshot() -> dict:
    """This process's elastic-training totals (the trainer driver's)."""
    with _train_elastic_lock:
        return dict(_train_elastic)


# ---------- disaggregated-serving counters ----------
# Same counter-style gauge pattern as the elastic trainer: the router
# (and each pool replica, for its own events) is the single writer of
# its process-local totals.

_serve_disagg_lock = threading.Lock()
_serve_disagg = {"streams_started": 0, "streams_completed": 0,
                 "stream_resumes": 0, "streams_evacuated": 0,
                 "fallback_reprefills": 0, "prefix_full_hits": 0,
                 "prefix_partial_hits": 0}
_serve_disagg_gauges: dict = {}


def _serve_disagg_gauge() -> Gauge:
    with _serve_disagg_lock:
        if "events" not in _serve_disagg_gauges:
            _serve_disagg_gauges["events"] = Gauge(
                "ray_tpu_serve_disagg_events_total",
                "disaggregated-serving lifecycle events "
                "(streams, resumes, evacuations, prefix-cache hits)",
                tag_keys=("event",))
    return _serve_disagg_gauges["events"]


def note_serve_disagg(event: str, n: int = 1) -> None:
    """Record n disaggregated-serving events (a key of _serve_disagg)
    and push the totals so a scrape mid-incident sees them."""
    g = _serve_disagg_gauge()
    with _serve_disagg_lock:
        if event not in _serve_disagg:
            return
        _serve_disagg[event] += int(n)
        val = _serve_disagg[event]
    g.set(val, tags={"event": event})
    flush_registry_now()


def serve_disagg_snapshot() -> dict:
    """This process's disaggregated-serving totals."""
    with _serve_disagg_lock:
        return dict(_serve_disagg)


def get_metrics_snapshot() -> dict:
    """Read all published metrics from the GCS (one entry per worker)."""
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()
    keys = cw._run(cw.gcs.call("KVKeys", {"ns": "metrics", "prefix": b""}))["keys"]
    out = {}
    for k in keys:
        v = cw._run(cw.gcs.call("KVGet", {"ns": "metrics", "key": k}))["value"]
        if v:
            out[k.decode()] = json.loads(v)
    return out


def _prom_escape(v) -> str:
    # Prometheus text-format label-value escaping: backslash, quote, newline.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(tag_json: str, extra: dict[str, str]) -> str:
    pairs = dict(tuple(p) for p in json.loads(tag_json))
    pairs.update(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_prom_escape(v)}"'
                    for k, v in sorted(pairs.items()))
    return "{" + body + "}"


def prometheus_text() -> str:
    """Render every published metric + built-in cluster gauges in the
    Prometheus text exposition format (parity: reference metrics agent →
    prometheus_exporter.py endpoint scraped by Prometheus)."""
    import ray_tpu

    lines: list[str] = []

    # Built-in cluster gauges.
    try:
        nodes = ray_tpu.nodes()
        alive = [n for n in nodes if n["alive"]]
        lines.append("# TYPE ray_tpu_cluster_nodes_alive gauge")
        lines.append(f"ray_tpu_cluster_nodes_alive {len(alive)}")
        for field, name in (("total_resources", "total"),
                            ("available_resources", "available")):
            lines.append(f"# TYPE ray_tpu_cluster_resources_{name} gauge")
            agg: dict[str, float] = {}
            for n in alive:
                for k, v in n[field].items():
                    agg[k] = agg.get(k, 0.0) + v
            for k, v in sorted(agg.items()):
                lines.append(
                    f'ray_tpu_cluster_resources_{name}{{resource="{k}"}} {v}')
    except Exception:
        pass

    # Group by metric family across workers: the exposition format requires
    # every sample of a family under ONE TYPE/HELP block.
    families: dict[str, list[tuple[str, dict]]] = {}
    for worker_key, metrics in sorted(get_metrics_snapshot().items()):
        worker = worker_key.split(":", 1)[-1][:12]
        for name, m in metrics.items():
            families.setdefault(name, []).append((worker, m))

    for name, series in sorted(families.items()):
        first = series[0][1]
        mtype = {"Counter": "counter", "Gauge": "gauge",
                 "Histogram": "histogram"}.get(first["type"], "untyped")
        if first.get("description"):
            lines.append(f"# HELP {name} {first['description']}")
        lines.append(f"# TYPE {name} {mtype}")
        for worker, m in series:
            if mtype == "histogram":
                bounds = m.get("boundaries", [])
                for tag_json, counts in m.get("counts", {}).items():
                    cum = 0
                    for b, c in zip(bounds + [float("inf")], counts):
                        cum += c
                        le = "+Inf" if b == float("inf") else repr(b)
                        lines.append(
                            f"{name}_bucket"
                            f"{_prom_labels(tag_json, {'worker': worker, 'le': le})}"
                            f" {cum}")
                    lines.append(
                        f"{name}_count"
                        f"{_prom_labels(tag_json, {'worker': worker})} {cum}")
                for tag_json, s in m.get("sums", {}).items():
                    lines.append(
                        f"{name}_sum"
                        f"{_prom_labels(tag_json, {'worker': worker})} {s}")
            else:
                for tag_json, v in m.get("values", {}).items():
                    lines.append(
                        f"{name}{_prom_labels(tag_json, {'worker': worker})}"
                        f" {v}")
    return "\n".join(lines) + "\n"


_pump_gauges: dict[str, Metric] | None = None
# pump_stats() is a cluster-wide RPC sweep — a fresh connect to every
# raylet — so scrape paths reuse one snapshot for a few seconds instead
# of sweeping per scrape (see _ttl_cached).
_pump_cache: dict = {"ts": float("-inf"), "snap": None}
_PUMP_CACHE_TTL_S = 5.0


def _ttl_cached(cache: dict, fetch) -> dict:
    """Shared TTL memo for cluster-sweep snapshots (pump stats, device
    plane): `cache` is a mutable {"ts", "snap"} cell owned by the call
    site; one refresh per TTL regardless of scrape rate."""
    now = time.monotonic()
    if cache.get("snap") is None or now - cache["ts"] >= _PUMP_CACHE_TTL_S:
        cache["snap"] = fetch()
        cache["ts"] = now
    return cache["snap"]


_device_cache: dict = {"ts": float("-inf"), "snap": None}
_latency_cache: dict = {"ts": float("-inf"), "snap": None}


def _device_summary_cached() -> dict:
    from ray_tpu.util import state as _state

    return _ttl_cached(_device_cache, _state.summarize_device_objects)


def _pump_stats_cached() -> dict:
    from ray_tpu.util import state as _state

    return _ttl_cached(_pump_cache, _state.pump_stats)


def export_pump_stats() -> dict:
    """Publish every daemon's event-loop stats as util.metrics gauges
    (per-handler call count / cumulative latency / max latency, plus
    loop drain + queue-depth gauges), tagged by daemon and RPC method.
    Returns the raw state.pump_stats() snapshot the gauges were built
    from. Parity: the reference exports event_stats.h counters through
    metric_defs.cc `operation_count`/`operation_run_time_ms`."""
    global _pump_gauges
    if _pump_gauges is None:
        _pump_gauges = {
            "calls": Gauge("ray_tpu_pump_handler_calls",
                           "RPC handler invocations per daemon event loop",
                           ("daemon", "method")),
            "errors": Gauge("ray_tpu_pump_handler_errors",
                            "RPC handler invocations that raised",
                            ("daemon", "method")),
            "cum_ms": Gauge("ray_tpu_pump_handler_latency_ms_total",
                            "cumulative handler latency per method (ms)",
                            ("daemon", "method")),
            "max_ms": Gauge("ray_tpu_pump_handler_latency_ms_max",
                            "max single-dispatch handler latency (ms)",
                            ("daemon", "method")),
            "drains": Gauge("ray_tpu_pump_drains",
                            "event-loop drain callbacks (loop wakeups)",
                            ("daemon",)),
            "events": Gauge("ray_tpu_pump_events",
                            "events pulled across all drains", ("daemon",)),
            "queue_depth": Gauge("ray_tpu_pump_queue_depth",
                                 "in-flight async dispatches (last seen)",
                                 ("daemon",)),
            "native_handled": Gauge(
                "ray_tpu_pump_native_handled",
                "frames handled by the in-pump native service",
                ("daemon",)),
        }
    snap = _pump_stats_cached()
    daemons = [("gcs", snap.get("gcs") or {})]
    for r in snap.get("raylets") or []:
        if "server" in r:
            daemons.append((f"raylet-{str(r.get('node_id', '?'))[:8]}", r))
    g = _pump_gauges
    for daemon, stats in daemons:
        server = stats.get("server") or {}
        for method, h in (server.get("handlers") or {}).items():
            tags = {"daemon": daemon, "method": method}
            g["calls"].set(h["count"], tags=tags)
            g["errors"].set(h["errors"], tags=tags)
            g["cum_ms"].set(h["cum_ms"], tags=tags)
            g["max_ms"].set(h["max_ms"], tags=tags)
        loop = server.get("loop") or {}
        g["drains"].set(loop.get("drains", 0), tags={"daemon": daemon})
        g["events"].set(loop.get("events", 0), tags={"daemon": daemon})
        g["queue_depth"].set(loop.get("queue_depth", 0),
                             tags={"daemon": daemon})
        native = stats.get("native")
        if native:
            g["native_handled"].set(native.get("handled", 0),
                                    tags={"daemon": daemon})
    # Synchronous publish of the complete batch: the throttled per-set
    # flush would snapshot mid-update and race the exposition's KV read,
    # leaving the rendered pump families one scrape behind.
    flush_registry_now()
    return snap


def core_prometheus_text() -> str:
    """Core-runtime metrics in Prometheus exposition format (parity:
    reference src/ray/stats/metric_defs.cc per-component instrumentation
    exported through the metrics agent): per-node scheduler/worker-pool/
    object-store gauges plus cluster-level actor/task state counts."""
    from ray_tpu.util import state as _state

    lines = []

    def gauge(name, help_, samples):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} gauge")
        for labels, value in samples:
            lab = ",".join(f'{k}="{v}"' for k, v in labels.items())
            lines.append(f"{name}{{{lab}}} {value}")

    try:
        stats = _state.node_stats()
    except Exception:
        stats = []
    ok = [st for st in stats if "error" not in st]
    nid = lambda st: {"node_id": str(st.get("node_id", "?"))[:12]}
    gauge("ray_tpu_node_workers", "worker processes per node",
          [(nid(st), st.get("num_workers", 0)) for st in ok])
    gauge("ray_tpu_node_idle_workers", "idle pool workers per node",
          [(nid(st), st.get("idle_workers", 0)) for st in ok])
    gauge("ray_tpu_node_pending_leases", "queued lease requests per node",
          [(nid(st), st.get("pending_leases", 0)) for st in ok])
    gauge("ray_tpu_node_leases_granted_total", "leases granted since boot",
          [(nid(st), st.get("leases_granted", 0)) for st in ok])
    gauge("ray_tpu_store_bytes_in_use", "shm object store bytes in use",
          [(nid(st), st.get("store", {}).get("bytes_in_use", 0))
           for st in ok])
    gauge("ray_tpu_store_num_objects", "objects resident in the shm store",
          [(nid(st), st.get("store", {}).get("num_objects", 0))
           for st in ok])
    gauge("ray_tpu_spilled_bytes", "bytes currently spilled",
          [(nid(st), st.get("spilled_bytes", 0)) for st in ok])
    for key, avail in (("CPU", "cpu"), ("TPU", "tpu")):
        gauge(f"ray_tpu_node_{avail}_available", f"available {key} per node",
              [(nid(st), st.get("available", {}).get(key, 0)) for st in ok])
    # Drain ladder: node states plus per-drain evacuation accounting
    # (duration, evacuated bytes/objects, respilled leases, migrated
    # actors) straight from the GCS node table's drain_stats.
    try:
        import ray_tpu as _rt

        nodes = _rt.nodes()
        by_state: dict = {}
        for n in nodes:
            by_state[n.get("state", "?")] = \
                by_state.get(n.get("state", "?"), 0) + 1
        gauge("ray_tpu_nodes_by_state",
              "nodes per lifecycle state "
              "(ALIVE/SUSPECT/DRAINING/DRAINED/DEAD)",
              [({"state": k}, v) for k, v in sorted(by_state.items())])
        suspect = [n for n in nodes if n.get("state") == "SUSPECT"]
        lines.append("# HELP ray_tpu_nodes_suspect nodes whose GCS "
                     "connection is lost, inside the re-registration "
                     "grace window (excluded from new placement)")
        lines.append("# TYPE ray_tpu_nodes_suspect gauge")
        lines.append(f"ray_tpu_nodes_suspect {len(suspect)}")
        recoveries = [({"node_id": str(n.get("node_id", "?"))[:12]},
                       n.get("suspect_recoveries", 0))
                      for n in nodes if n.get("suspect_recoveries")]
        if recoveries:
            gauge("ray_tpu_node_suspect_recoveries_total",
                  "times this node re-registered inside the SUSPECT "
                  "grace window (partition flaps survived)",
                  recoveries)
        drain_rows = [(n, n.get("drain_stats") or {}) for n in nodes]
        drain_rows = [(n, d) for n, d in drain_rows if d]
        nlab = lambda n: {"node_id": str(n.get("node_id", "?"))[:12],
                          "reason": n.get("drain_reason", "")}
        for metric, key, help_ in (
                ("ray_tpu_drain_duration_seconds", "duration_s",
                 "wall time one node's drain evacuation took"),
                ("ray_tpu_drain_evacuated_bytes", "evacuated_bytes",
                 "object-store bytes pushed to peers during drain"),
                ("ray_tpu_drain_evacuated_objects", "evacuated_objects",
                 "object-store objects pushed to peers during drain"),
                ("ray_tpu_drain_evacuated_device_objects",
                 "evacuated_device_objects",
                 "HBM-pinned arrays re-homed during drain"),
                ("ray_tpu_drain_respilled_leases", "respilled_leases",
                 "queued leases re-spilled to peers during drain"),
                ("ray_tpu_drain_killed_leases", "killed_leases",
                 "running leases failed retryable at the drain deadline"),
                ("ray_tpu_drain_migrated_actors", "migrated_actors",
                 "actors proactively restarted off draining nodes")):
            samples = [(nlab(n), d.get(key, 0)) for n, d in drain_rows
                       if key in d]
            if samples:
                gauge(metric, help_, samples)
    except Exception:
        pass
    # Resilient-session counters, per raylet (each daemon's process-
    # global rpc.session_stats(): reconnects it performed as a client,
    # replays it sent, retried requests it answered from the reply
    # cache as a server).
    for metric, help_, key in (
            ("ray_tpu_rpc_reconnects_total",
             "resilient-session reconnects since daemon boot",
             "reconnects_total"),
            ("ray_tpu_rpc_replayed_requests_total",
             "un-acked requests replayed after a session reconnect",
             "replayed_requests_total"),
            ("ray_tpu_rpc_deduped_requests_total",
             "retried requests answered from the (session_id, seq) "
             "reply cache instead of re-executing",
             "deduped_requests_total")):
        samples = [(nid(st), st.get("rpc_sessions", {}).get(key, 0))
                   for st in ok if st.get("rpc_sessions")]
        if samples:
            gauge(metric, help_, samples)
    # Native control plane (default-on): per-daemon fallthrough /
    # degraded / stale-epoch counters plus the per-method split, so a
    # tripped divergence breaker — or a quietly fallthrough-heavy
    # workload — shows on a dashboard, not just in daemon logs.
    try:
        planes = []
        try:
            nc = _state.cluster_status().get("native_control")
            if nc:
                planes.append(("gcs", nc))
        except Exception:
            pass
        for st in ok:
            rnc = st.get("native_control")
            if rnc:
                planes.append(
                    (f"raylet-{str(st.get('node_id', '?'))[:12]}", rnc))
        for metric, key, help_ in (
                ("ray_tpu_native_handled_total", "handled_total",
                 "frames handled by the native control plane"),
                ("ray_tpu_native_fallthrough_total",
                 "native_fallthrough_total",
                 "owned-method frames routed to the Python handlers "
                 "(complex shapes, transient states)"),
                ("ray_tpu_native_degraded_total",
                 "native_degraded_total",
                 "frames pushed back to Python by the divergence "
                 "breaker"),
                ("ray_tpu_native_stale_epoch_rejections_total",
                 "stale_epoch_rejections_total",
                 "pre-restart replays rejected by the session-epoch "
                 "handshake (the client re-issues)"),
                ("ray_tpu_native_divergence_trips_total",
                 "divergence_trips_total",
                 "times the native<->Python mirror audit tripped the "
                 "degradation breaker")):
            samples = [({"daemon": d}, p.get(key, 0)) for d, p in planes]
            if samples:
                gauge(metric, help_, samples)
        for metric, key, help_ in (
                ("ray_tpu_native_method_handled_total", "handled",
                 "frames handled natively, per owned method"),
                ("ray_tpu_native_method_routed_total", "routed",
                 "frames routed to Python, per owned method"),
                ("ray_tpu_native_method_degraded_total", "degraded",
                 "breaker-degraded frames, per owned method")):
            samples = [({"daemon": d, "method": m}, ms.get(key, 0))
                       for d, p in planes
                       for m, ms in (p.get("methods") or {}).items()]
            if samples:
                gauge(metric, help_, samples)
    except Exception:
        pass
    # Pubsub fanout backpressure (issue 20): per-subscriber bounded
    # coalescing queues on the GCS Python fallback path, plus the
    # native path's batch count and the streaming-recovery flag.
    try:
        cs = _state.cluster_status()
        fo = cs.get("fanout") or {}
        for metric, key, help_ in (
                ("ray_tpu_gcs_fanout_enqueued_total", "enqueued",
                 "pubsub messages enqueued to subscriber send queues"),
                ("ray_tpu_gcs_fanout_sent_total", "sent",
                 "pubsub messages delivered by subscriber sender tasks"),
                ("ray_tpu_gcs_fanout_coalesced_total", "coalesced",
                 "queued state messages superseded latest-wins per "
                 "entity before delivery"),
                ("ray_tpu_gcs_fanout_dropped_total", "dropped",
                 "messages dropped oldest-first past the per-subscriber "
                 "queue bound"),
                ("ray_tpu_gcs_fanout_batches_total", "batches",
                 "sender drain cycles on the Python fanout path"),
                ("ray_tpu_gcs_fanout_native_batches_total",
                 "native_batches",
                 "fanout batches handed to the native in-pump service"),
                ("ray_tpu_gcs_fanout_queue_max_depth", "max_depth",
                 "high-water mark of any subscriber send queue")):
            if key in fo:
                lines.append(f"# HELP {metric} {help_}")
                lines.append(f"# TYPE {metric} gauge")
                lines.append(f"{metric} {fo[key]}")
        lines.append("# HELP ray_tpu_gcs_recovering 1 while a restarted "
                     "GCS is still streaming persisted state in the "
                     "background (answers/grants already flowing)")
        lines.append("# TYPE ray_tpu_gcs_recovering gauge")
        lines.append(
            f"ray_tpu_gcs_recovering {1 if cs.get('recovering') else 0}")
    except Exception:
        pass
    try:
        actors = _state.summarize_actors()["by_state"]
        gauge("ray_tpu_actors", "actors by state",
              [({"state": k}, v) for k, v in actors.items()])
    except Exception:
        pass
    try:
        tasks = _state.summarize_tasks()["by_state"]
        gauge("ray_tpu_tasks", "task events by state",
              [({"state": k}, v) for k, v in tasks.items()])
    except Exception:
        pass
    # Device object plane: cluster-wide pinned-HBM gauges (the registry
    # gauges each worker publishes cover its own process; this block
    # aggregates the raylet fan-out for one-scrape cluster totals).
    # Cached like pump_stats: the fan-out is a fresh RPC to every raylet
    # (which fans to every worker) — the scrape path must not pay that
    # per request.
    try:
        dev = _device_summary_cached()
        gauge("ray_tpu_device_plane_pinned_bytes",
              "bytes pinned in HBM by the device object plane, per node",
              [({"node_id": str(n.get("node_id", "?"))[:12]},
                n.get("pinned_bytes", 0))
               for n in dev["per_node"] if "error" not in n])
        gauge("ray_tpu_device_plane_pinned_objects",
              "arrays pinned by the device object plane, per node",
              [({"node_id": str(n.get("node_id", "?"))[:12]},
                n.get("pinned_objects", 0))
               for n in dev["per_node"] if "error" not in n])
    except Exception:
        pass
    # Keep this process's own device-plane registry gauges current so
    # prometheus_text renders this scrape's values.
    try:
        from ray_tpu._private.device_objects import (
            export_device_object_gauges)

        export_device_object_gauges()
    except Exception:
        pass
    # Event-loop/pump stats per daemon (analogue of the reference's
    # event_stats.h exported through metric_defs.cc operation_* series).
    # Published ONLY through the registry gauges (rendered by
    # prometheus_text) — emitting the same family names here too would
    # duplicate their TYPE blocks in the concatenated /metrics page,
    # which expfmt consumers reject wholesale.
    try:
        export_pump_stats()
    except Exception:
        pass
    # Per-stage task-lifecycle latency percentiles (families unique to
    # this exposition; bounded limit — the scrape path must not drag
    # the full 200k-row event table over RPC every 15s).
    try:
        # TTL-cached like the pump/device sweeps: a 20k-row ListTaskEvents
        # per scrape is GCS loop time the scrape path must not spend.
        lat = _ttl_cached(
            _latency_cache,
            lambda: _state.summarize_task_latency(limit=20000))
        for pct in ("p50_ms", "p95_ms", "p99_ms"):
            gauge(f"ray_tpu_task_stage_{pct}",
                  f"task lifecycle stage latency {pct[:-3]} (ms)",
                  [({"stage": s}, v[pct])
                   for s, v in lat["stages"].items()])
    except Exception:
        pass
    return "\n".join(lines) + "\n"
