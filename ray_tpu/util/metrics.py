"""User-defined metrics: Counter / Gauge / Histogram.

Parity: reference python/ray/util/metrics.py:19. The reference exports via
OpenCensus → node metrics agent → Prometheus; here metrics publish to the
GCS KV (namespace "metrics") so any process (dashboard-lite, tests, a
Prometheus bridge) can scrape one place.
"""

from __future__ import annotations

import bisect
import json
import threading
import time

from ray_tpu._private.api_internal import core_worker_or_none

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}
_last_flush = [0.0]
_FLUSH_INTERVAL_S = 1.0


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict[str, str] = {}
        self._values: dict[tuple, float] = {}
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: dict[str, str] | None) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _flush_maybe(self):
        now = time.monotonic()
        if now - _last_flush[0] < _FLUSH_INTERVAL_S:
            return
        _last_flush[0] = now
        cw = core_worker_or_none()
        if cw is None or cw.gcs is None or cw.gcs.closed:
            return
        with _registry_lock:
            snapshot = {name: m.snapshot() for name, m in _registry.items()}
        try:
            cw._spawn(cw.gcs.call("KVPut", {
                "ns": "metrics",
                "key": f"worker:{cw.worker_id}".encode(),
                "value": json.dumps(snapshot).encode()}))
        except Exception:
            pass

    def snapshot(self) -> dict:
        return {"type": type(self).__name__, "description": self.description,
                "values": {json.dumps(k): v for k, v in self._values.items()}}


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: dict | None = None):
        key = self._tag_tuple(tags)
        self._values[key] = self._values.get(key, 0.0) + value
        self._flush_maybe()


class Gauge(Metric):
    def set(self, value: float, tags: dict | None = None):
        self._values[self._tag_tuple(tags)] = value
        self._flush_maybe()


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: list[float] | None = None,
                 tag_keys: tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or [0.1, 1, 10, 100, 1000])
        self._counts: dict[tuple, list[int]] = {}

    def observe(self, value: float, tags: dict | None = None):
        key = self._tag_tuple(tags)
        counts = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
        counts[bisect.bisect_left(self.boundaries, value)] += 1
        self._values[key] = value  # last observation
        self._flush_maybe()

    def snapshot(self) -> dict:
        base = super().snapshot()
        base["boundaries"] = self.boundaries
        base["counts"] = {json.dumps(k): v for k, v in self._counts.items()}
        return base


def get_metrics_snapshot() -> dict:
    """Read all published metrics from the GCS (one entry per worker)."""
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()
    keys = cw._run(cw.gcs.call("KVKeys", {"ns": "metrics", "prefix": b""}))["keys"]
    out = {}
    for k in keys:
        v = cw._run(cw.gcs.call("KVGet", {"ns": "metrics", "key": k}))["value"]
        if v:
            out[k.decode()] = json.loads(v)
    return out
