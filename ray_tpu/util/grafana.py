"""Grafana dashboard generation from the metric registry.

Parity: reference dashboard/modules/metrics/ — the reference ships
pre-built Grafana dashboard JSON (grafana_dashboard_factory.py builds
"default" and "serve" dashboards from panel templates targeting the
Prometheus datasource). Here panels are generated from what is actually
registered: every `ray_tpu.util.metrics` Counter becomes a rate() graph,
every Gauge a timeseries, every Histogram a p50/p95/p99
histogram_quantile panel, plus a fixed core-health row (nodes, workers,
task throughput) that exists whether or not user code registered
metrics. Serve at `/api/grafana/dashboard` (dashboard.py) or write to
disk for provisioning:

    from ray_tpu.util.grafana import write_dashboard
    write_dashboard("/etc/grafana/provisioning/dashboards/ray_tpu.json")
"""

from __future__ import annotations

import json

from ray_tpu.util import metrics as _metrics

# Core panels always present. Every expr targets a metric name the
# dashboard's /metrics endpoint actually emits (ray_tpu/util/metrics.py
# exporter — names verified against it; tests/test_job_dashboard.py
# cross-checks the two stay in sync).
_CORE_PANELS = [
    ("Cluster nodes", "gauge", "ray_tpu_cluster_nodes_alive"),
    ("Workers per node", "timeseries", "ray_tpu_node_workers"),
    ("Lease queue depth", "timeseries", "ray_tpu_node_pending_leases"),
    ("Task throughput", "timeseries",
     'rate(ray_tpu_tasks{state="FINISHED"}[1m])'),
    ("Object store bytes", "timeseries", "ray_tpu_store_bytes_in_use"),
    ("Actors by state", "timeseries", "ray_tpu_actors"),
]


def _panel(panel_id: int, title: str, kind: str, expr: str,
           x: int, y: int) -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "gauge" if kind == "gauge" else "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "targets": [{"expr": expr, "refId": "A",
                     "legendFormat": "{{instance}}"}],
    }


def generate_dashboard(title: str = "ray_tpu") -> dict:
    """Build a complete Grafana dashboard JSON model (schema v36-ish —
    importable via the Grafana UI or file provisioning)."""
    panels = []
    pid = 1
    x = y = 0

    def place(title_, kind, expr):
        nonlocal pid, x, y
        panels.append(_panel(pid, title_, kind, expr, x, y))
        pid += 1
        x = 12 if x == 0 else 0
        if x == 0:
            y += 8

    for title_, kind, expr in _CORE_PANELS:
        place(title_, kind, expr)

    with _metrics._registry_lock:
        registered = {name: type(m).__name__
                      for name, m in _metrics._registry.items()}
    for name, kind in sorted(registered.items()):
        if kind == "Counter":
            place(f"{name} (rate)", "timeseries", f"rate({name}_total[1m])")
        elif kind == "Histogram":
            for q in ("0.5", "0.95", "0.99"):
                place(f"{name} p{int(float(q) * 100)}", "timeseries",
                      f"histogram_quantile({q}, "
                      f"rate({name}_bucket[5m]))")
        else:
            place(name, "timeseries", name)

    return {
        "title": title,
        "uid": f"{title}-autogen",
        "schemaVersion": 36,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus", "label": "Datasource",
        }]},
        "panels": panels,
    }


def write_dashboard(path: str, title: str = "ray_tpu") -> str:
    with open(path, "w") as f:
        json.dump(generate_dashboard(title), f, indent=2)
    return path
