"""Dask-on-ray_tpu scheduler: execute dask task graphs as cluster tasks.

Parity: reference python/ray/util/dask/scheduler.py — `ray_dask_get` is
a drop-in dask scheduler (`dask.compute(..., scheduler=ray_dask_get)` /
`enable_dask_on_ray()`): every dask task becomes a cluster task, graph
edges become ObjectRef arguments, so the cluster's scheduler provides
the parallelism and the object store carries intermediate results.

Re-design note: the dask GRAPH protocol is plain data — a dict mapping
keys to either literals, keys, or `(callable, arg, ...)` task tuples
(nested freely) — so the scheduler here implements the graph walk
itself and works on hand-built graphs even when dask is not installed
(it is not in this image; `enable_dask_on_ray` needs the real dask and
stays dep-gated, hermetic tests drive `ray_dask_get` directly).
"""

from __future__ import annotations

from typing import Any

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray"]


def _istask(x) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


@ray_tpu.remote
def _dask_task(func_and_args_blob: bytes, *refs):
    """Execute one dask task: rebuild the (func, args) spec, substituting
    resolved upstream values (passed as task args so the runtime fetched
    them already) back into their graph positions."""
    from ray_tpu._private import serialization

    spec, positions = serialization.loads_func(func_and_args_blob)
    resolved = list(refs)

    def rebuild(node, path=()):
        if path in positions:
            return resolved[positions[path]]
        if isinstance(node, tuple) and node and callable(node[0]):
            return node[0](*[rebuild(a, path + (i,))
                             for i, a in enumerate(node[1:], 1)])
        if isinstance(node, (list, tuple)):
            t = type(node)
            return t(rebuild(a, path + (i,)) for i, a in enumerate(node))
        return node

    return rebuild(spec)


def ray_dask_get(dsk: dict, keys, **kwargs) -> Any:
    """Dask scheduler entry point (reference: scheduler.py ray_dask_get).

    Walks the graph bottom-up in dependency order, submitting one
    cluster task per dask task; sub-graph edges pass as ObjectRefs so
    downstream tasks start the moment their inputs land, with zero
    driver round-trips for intermediates."""
    from ray_tpu._private import serialization

    refs: dict[Any, Any] = {}

    def key_deps(node, path=(), out=None):
        """(path, key) pairs for every graph-key reference inside a task
        spec (dask nests keys arbitrarily deep in args)."""
        if out is None:
            out = []
        if _istask(node):
            for i, a in enumerate(node[1:], 1):
                key_deps(a, path + (i,), out)
        elif isinstance(node, (list, tuple)):
            for i, a in enumerate(node):
                key_deps(a, path + (i,), out)
        else:
            try:
                if node in dsk and path:
                    out.append((path, node))
            except TypeError:
                pass  # unhashable literal
        return out

    def materialize(key):
        if key in refs:
            return refs[key]
        node = dsk[key]
        if _istask(node):
            deps = key_deps(node)
            positions = {path: i for i, (path, _) in enumerate(deps)}
            dep_refs = [materialize(k) for _, k in deps]
            # cloudpickle: dask graphs carry closures/lambdas routinely.
            blob = serialization.dumps_func((node, positions))
            refs[key] = _dask_task.remote(blob, *dep_refs)
        elif isinstance(node, (str, bytes, int, float, frozenset, tuple)) \
                and _hashable(node) and node in dsk and node != key:
            refs[key] = materialize(node)  # alias: key -> key
        else:
            refs[key] = ray_tpu.put(node)  # literal
        return refs[key]

    def _hashable(x):
        try:
            hash(x)
            return True
        except TypeError:
            return False

    def resolve(keyspec):
        # dask's get contract: keys may be nested lists mirroring the
        # desired output structure.
        if isinstance(keyspec, list):
            return [resolve(k) for k in keyspec]
        return ray_tpu.get(materialize(keyspec), timeout=600)

    return resolve(keys)


def enable_dask_on_ray():
    """Install ray_dask_get as dask's default scheduler (dep-gated:
    requires the real dask; reference scheduler.py enable_dask_on_ray).
    Returns the dask config context manager."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires dask; pass scheduler=ray_dask_get "
            "to dask.compute directly, or install dask") from e
    return dask.config.set(scheduler=ray_dask_get)
