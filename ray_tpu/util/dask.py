"""Dask-on-ray_tpu scheduler: execute dask task graphs as cluster tasks.

Parity: reference python/ray/util/dask/scheduler.py — `ray_dask_get` is
a drop-in dask scheduler (`dask.compute(..., scheduler=ray_dask_get)` /
`enable_dask_on_ray()`): every dask task becomes a cluster task, graph
edges become ObjectRef arguments, so the cluster's scheduler provides
the parallelism and the object store carries intermediate results.

Re-design note: the dask GRAPH protocol is plain data — a dict mapping
keys to either literals, keys, or `(callable, arg, ...)` task tuples
(nested freely) — so the scheduler here implements the graph walk
itself and works on hand-built graphs even when dask is not installed
(it is not in this image; `enable_dask_on_ray` needs the real dask and
stays dep-gated, hermetic tests drive `ray_dask_get` directly).
"""

from __future__ import annotations

from typing import Any

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray"]


def _istask(x) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


@ray_tpu.remote
def _dask_task(func_and_args_blob: bytes, *refs):
    """Execute one dask task: rebuild the (func, args) spec, substituting
    resolved upstream values (passed as task args so the runtime fetched
    them already) back into their graph positions."""
    from ray_tpu._private import serialization

    spec, positions = serialization.loads_func(func_and_args_blob)
    resolved = list(refs)

    def rebuild(node, path=()):
        if path in positions:
            return resolved[positions[path]]
        if isinstance(node, tuple) and node and callable(node[0]):
            return node[0](*[rebuild(a, path + (i,))
                             for i, a in enumerate(node[1:], 1)])
        if isinstance(node, (list, tuple)):
            t = type(node)
            return t(rebuild(a, path + (i,)) for i, a in enumerate(node))
        return node

    return rebuild(spec)


def ray_dask_get(dsk: dict, keys, **kwargs) -> Any:
    """Dask scheduler entry point (reference: scheduler.py ray_dask_get).

    Walks the graph bottom-up in dependency order, submitting one
    cluster task per dask task; sub-graph edges pass as ObjectRefs so
    downstream tasks start the moment their inputs land, with zero
    driver round-trips for intermediates."""
    from ray_tpu._private import serialization

    refs: dict[Any, Any] = {}
    get_timeout = kwargs.get("get_timeout")

    def _hashable(x):
        try:
            hash(x)
            return True
        except TypeError:
            return False

    def is_key(x) -> bool:
        # Dask's rule (dask.core ishashable + `in dsk`), checked BEFORE
        # any recursion: tuple keys like ("x", 0) — the key format of
        # every dask.array/dataframe/bag graph — are key references,
        # not literal tuples to recurse into.
        return _hashable(x) and x in dsk

    def key_deps(node, path=(), out=None):
        """(path, key) pairs for every graph-key reference inside a
        value — task args, nested containers, or a bare list of keys."""
        if out is None:
            out = []
        if path and is_key(node):
            out.append((path, node))
        elif _istask(node):
            for i, a in enumerate(node[1:], 1):
                key_deps(a, path + (i,), out)
        elif isinstance(node, (list, tuple)):
            for i, a in enumerate(node):
                key_deps(a, path + (i,), out)
        return out

    def materialize(root):
        """Iterative dependency walk (a deep linear chain must not hit
        the recursion limit) with cycle detection."""
        if root in refs:
            return refs[root]
        stack = [root]
        onstack = {root}
        while stack:
            k = stack[-1]
            if k in refs:
                stack.pop()
                onstack.discard(k)
                continue
            node = dsk[k]
            alias = is_key(node) and node != k
            dep_keys = [node] if alias else [d for _, d in key_deps(node)]
            # ONE unresolved dep at a time: the stack then IS the DFS
            # path, so the onstack check flags true cycles only (pushing
            # all deps at once made queued SIBLINGS look like ancestors).
            unresolved = next((d for d in dep_keys if d not in refs), None)
            if unresolved is not None:
                if unresolved in onstack:
                    raise ValueError(
                        f"cycle in dask graph involving {unresolved!r}")
                stack.append(unresolved)
                onstack.add(unresolved)
                continue
            if alias:
                refs[k] = refs[node]
            else:
                deps = key_deps(node)
                if deps or _istask(node):
                    # Anything with embedded keys (task tuples AND bare
                    # containers of keys) executes remotely so the
                    # substitution happens where the values are.
                    positions = {path: i
                                 for i, (path, _) in enumerate(deps)}
                    # cloudpickle: dask graphs carry closures/lambdas.
                    blob = serialization.dumps_func((node, positions))
                    refs[k] = _dask_task.remote(
                        blob, *[refs[d] for _, d in deps])
                else:
                    refs[k] = ray_tpu.put(node)  # literal
            stack.pop()
            onstack.discard(k)
        return refs[root]

    # Submit EVERYTHING first, then one batched get: independent
    # subgraphs must run concurrently, not serialize behind per-key
    # driver round-trips. `keys` may be nested lists mirroring the
    # desired output structure (dask's get contract).
    flat: list = []

    def build(keyspec):
        if isinstance(keyspec, list):
            return [build(k) for k in keyspec]
        flat.append(materialize(keyspec))
        return len(flat) - 1

    shape = build(keys)
    values = ray_tpu.get(flat, timeout=get_timeout) if flat else []

    def fill(sh):
        if isinstance(sh, list):
            return [fill(x) for x in sh]
        return values[sh]

    return fill(shape)


def enable_dask_on_ray():
    """Install ray_dask_get as dask's default scheduler (dep-gated:
    requires the real dask; reference scheduler.py enable_dask_on_ray).
    Returns the dask config context manager."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires dask; pass scheduler=ray_dask_get "
            "to dask.compute directly, or install dask") from e
    return dask.config.set(scheduler=ray_dask_get)
