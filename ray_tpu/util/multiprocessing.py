"""multiprocessing.Pool-compatible API over ray_tpu tasks.

Parity: reference python/ray/util/multiprocessing/pool.py — a drop-in
`Pool` whose workers are cluster actors, so `pool.map` fans out across
nodes instead of local forks. Chunking semantics follow the stdlib: the
iterable is split into chunks, each chunk is one remote task.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Iterable

import ray_tpu

__all__ = ["Pool"]


@ray_tpu.remote
class _PoolWorker:
    def run_chunk(self, fn, chunk, star: bool, extra_args, extra_kwargs):
        if star:
            return [fn(*item) for item in chunk]
        return [fn(item, *extra_args, **extra_kwargs) for item in chunk]


class AsyncResult:
    """Handle on an in-flight map/apply (stdlib AsyncResult shape)."""

    def __init__(self, refs: list, single: bool = False):
        self._refs = refs
        self._single = single

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs)
            return True
        except Exception:
            return False

    def get(self, timeout: float | None = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        flat = [x for chunk in chunks for x in chunk]
        return flat[0] if self._single else flat


class Pool:
    """Process pool backed by cluster actors.

    `processes=None` sizes the pool to the cluster's CPU count, like the
    stdlib sizes to os.cpu_count().
    """

    def __init__(self, processes: int | None = None,
                 initializer: Callable | None = None,
                 initargs: tuple = (), ray_remote_args: dict | None = None):
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._size = processes
        opts = dict(ray_remote_args or {})
        self._workers = [_PoolWorker.options(**opts).remote()
                         for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._inflight: list = []  # refs close()/join() must drain
        if initializer is not None:
            # Initializers run once per worker (stdlib semantics); results
            # are discarded.
            ray_tpu.get([
                w.run_chunk.remote(lambda _: initializer(*initargs), [None],
                                   False, (), {})
                for w in self._workers])

    # ---- submission ----

    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _submit_chunks(self, fn, iterable, chunksize, star: bool,
                       args=(), kwargs=None) -> list:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, math.ceil(len(items) / (self._size * 4)))
        refs = []
        for i in range(0, len(items), chunksize):
            w = self._workers[next(self._rr)]
            refs.append(w.run_chunk.remote(fn, items[i:i + chunksize], star,
                                           args, kwargs or {}))
        self._inflight.extend(refs)
        if len(self._inflight) > 512:  # prune completed refs
            _, pending = ray_tpu.wait(self._inflight,
                                      num_returns=len(self._inflight),
                                      timeout=0)
            self._inflight = pending
        return refs

    def apply(self, fn: Callable, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict | None = None) -> AsyncResult:
        self._check_running()
        w = self._workers[next(self._rr)]
        ref = w.run_chunk.remote(lambda _a, **_k: fn(*args, **(kwds or {})),
                                 [None], False, (), {})
        self._inflight.append(ref)
        return AsyncResult([ref], single=True)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: int | None = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: int | None = None) -> AsyncResult:
        self._check_running()
        return AsyncResult(self._submit_chunks(fn, iterable, chunksize, False))

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: int | None = None) -> list:
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn: Callable, iterable: Iterable,
                      chunksize: int | None = None) -> AsyncResult:
        self._check_running()
        return AsyncResult(self._submit_chunks(fn, iterable, chunksize, True))

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int | None = None):
        self._check_running()
        refs = self._submit_chunks(fn, iterable, chunksize, False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int | None = None):
        self._check_running()
        refs = self._submit_chunks(fn, iterable, chunksize, False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for r in ready:
                yield from ray_tpu.get(r)

    # ---- lifecycle ----

    def close(self):
        """Stop accepting work; in-flight tasks keep running (stdlib
        contract — join() then waits for them and reaps the workers)."""
        self._closed = True

    def terminate(self):
        self._closed = True
        for w in self._workers:
            ray_tpu.kill(w)
        self._workers = []
        self._inflight = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")
        if self._inflight:
            ray_tpu.wait(self._inflight, num_returns=len(self._inflight))
            self._inflight = []
        for w in self._workers:
            ray_tpu.kill(w)
        self._workers = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
