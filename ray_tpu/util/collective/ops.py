"""Device-plane collectives: XLA ops compiled into the program.

This is the TPU replacement for NCCL runtime calls (SURVEY.md §2.5 item 3):
inside `shard_map`/`pjit`, communication is expressed as `jax.lax`
collectives over named mesh axes and compiled by XLA to ICI transfers,
overlapped with compute by the scheduler. These wrappers give the
`ray.util.collective` vocabulary to code running inside a mapped function.

Example::

    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    import ray_tpu.util.collective.ops as col

    def step(x):
        return col.allreduce(x, axis="dp")

    shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P())(x)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def allreduce(x, axis: str | tuple[str, ...], op: str = "sum"):
    """psum/pmean/pmax/pmin over a mesh axis (lowers to an ICI all-reduce)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "product":
        return jnp.exp(lax.psum(jnp.log(x), axis))
    raise ValueError(f"unsupported reduce op {op!r}")


def allgather(x, axis: str, *, tiled: bool = False, gather_axis: int = 0):
    """all_gather over a mesh axis (ICI all-gather)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reducescatter(x, axis: str, *, scatter_axis: int = 0, op: str = "sum"):
    """psum_scatter over a mesh axis (ICI reduce-scatter)."""
    if op != "sum":
        raise ValueError("XLA reduce-scatter supports sum")
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def broadcast(x, axis: str, src_index: int = 0):
    """Broadcast src_index's shard to all members of the axis."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == src_index, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def alltoall(x, axis: str, *, split_axis: int, concat_axis: int):
    """all_to_all over a mesh axis (Ulysses-style sequence redistribution)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def permute(x, axis: str, perm: list[tuple[int, int]]):
    """ppermute: point-to-point shifts over the ICI ring (PP/ring-attention
    building block)."""
    return lax.ppermute(x, axis, perm)


def shift_right(x, axis: str):
    n = axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def shift_left(x, axis: str):
    n = axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Concrete size of a named mesh axis, across jax versions:
    lax.axis_size where it exists, else jax.core.axis_frame (which
    returns the int size on the 0.4.x line)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    from jax import core as _core

    return _core.axis_frame(axis)
