"""Collective communication API with a TPU-native XLA backend.

Parity: reference python/ray/util/collective/collective.py:120-655
(init_collective_group / allreduce / allgather / reducescatter / broadcast /
send / recv, GroupManager:40). The reference's backends are NCCL (cupy, with
a named-actor KV rendezvous, nccl_collective_group.py:28) and GLOO (pygloo).

TPU-native re-design (SURVEY.md §2.5): there are two planes —

1. the *device plane*: collectives lower to XLA ops compiled INTO the
   program (`jax.lax.psum/all_gather/ppermute/all_to_all` over ICI). Use
   `ray_tpu.util.collective.ops` inside `shard_map`/`pjit` — nothing to
   initialize; the mesh IS the group. This is the architectural difference
   from NCCL to embrace: no runtime library call, the compiler schedules
   communication with compute.

2. the *host plane* (this module's group API): processes (actors) form a
   group by rendezvous through a named actor in the GCS (replacing the
   reference's NCCL-unique-id rendezvous) and run collectives on host
   numpy arrays over the object-store/DCN path. On multi-host TPU pods the
   group init also performs the `jax.distributed.initialize` handshake so
   members can subsequently compile single multi-host XLA programs.
"""

from __future__ import annotations

import threading
import time

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda a, b: a + b,
    "product": lambda a, b: a * b,
    "min": np.minimum,
    "max": np.maximum,
}


class _PeerPlane:
    """Direct worker-to-worker transport for host collectives.

    The rendezvous actor coordinates MEMBERSHIP only; payloads flow
    peer-to-peer over each member's existing CoreWorker RPC server (a
    "CollectiveDeliver" handler feeding a mailbox). This is what lets
    allreduce scale past a handful of workers: a ring moves 2·(W-1)/W of
    the tensor per member regardless of W, where the actor funnel
    serialized W full tensors through one process (the round-2 advisor's
    scaling complaint; reference gloo rings behave the same way)."""

    def __init__(self):
        from ray_tpu._private.api_internal import get_core_worker

        self.cw = get_core_worker()
        self._cond = threading.Condition()
        self._inbox: dict[tuple, tuple] = {}
        self._conns: dict[tuple, object] = {}
        self.cw.server.handlers["CollectiveDeliver"] = self._on_deliver
        self.addr = [self.cw.address.host, self.cw.address.port]

    async def _on_deliver(self, conn, payload):
        key = (payload["group"], payload["tag"])
        with self._cond:
            self._inbox[key] = (payload["dtype"], payload["shape"],
                                payload["data"])
            self._cond.notify_all()
        return {}

    def _conn_for(self, addr):
        from ray_tpu._private import rpc

        key = tuple(addr)
        conn = self._conns.get(key)
        if conn is None or conn.closed:
            conn = self.cw._run(rpc.dial(
                addr[0], int(addr[1]), name="collective-peer"))
            self._conns[key] = conn
        return conn

    def send(self, group: str, addr, tag: str, arr: np.ndarray):
        conn = self._conn_for(addr)
        self.cw._run(conn.notify("CollectiveDeliver", {
            "group": group, "tag": tag, "dtype": str(arr.dtype),
            "shape": list(arr.shape), "data": arr.tobytes()}))

    def discard(self, group: str, tag: str) -> None:
        """Drop an undelivered mailbox entry (e.g. a device-plane
        collective transfer that degraded to the host path mid-batch —
        its already-sent payloads must not strand here forever)."""
        with self._cond:
            self._inbox.pop((group, tag), None)

    def recv(self, group: str, tag: str, timeout: float = 300.0
             ) -> np.ndarray:
        key = (group, tag)
        deadline = time.monotonic() + timeout
        with self._cond:
            while key not in self._inbox:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"collective recv timed out waiting for {tag!r}")
                self._cond.wait(remaining)
            dtype, shape, data = self._inbox.pop(key)
        # bf16/fp8 dtype names need ml_dtypes registered with numpy —
        # a jax-less consumer of a device-plane transfer must not crash.
        from ray_tpu._private.device_objects import _np_dtype

        return np.frombuffer(bytearray(data),
                             dtype=_np_dtype(dtype)).reshape(shape)

    def close(self):
        for conn in self._conns.values():
            try:
                self.cw._run(conn.close())
            except Exception:
                pass
        self._conns.clear()


_peer_plane: _PeerPlane | None = None
_peer_plane_lock = threading.Lock()


def _get_peer_plane() -> _PeerPlane:
    global _peer_plane
    with _peer_plane_lock:
        if _peer_plane is None:
            _peer_plane = _PeerPlane()
        return _peer_plane


@ray_tpu.remote
class _RendezvousActor:
    """Coordination point for one collective group (replaces the reference's
    NCCLUniqueIDStore named actor, nccl_collective_group.py:28)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.members: dict[int, dict] = {}
        self.rounds: dict[tuple, dict] = {}
        self.results: dict[tuple, object] = {}

    def join(self, rank: int, info: dict) -> dict:
        self.members[rank] = info
        return {"joined": len(self.members), "world_size": self.world_size}

    def num_members(self) -> int:
        return len(self.members)

    def members_info(self) -> dict:
        return self.members

    def contribute(self, round_key: str, op: str, rank: int, payload):
        """Gather contributions; when all present, compute + publish."""
        key = (round_key,)
        r = self.rounds.setdefault(key, {})
        r[rank] = payload
        if len(r) == self.world_size:
            ordered = [r[i] for i in range(self.world_size)]
            if op in _REDUCE_OPS:
                acc = ordered[0]
                f = _REDUCE_OPS[op]
                for x in ordered[1:]:
                    acc = f(acc, x)
                self.results[key] = acc
            elif op == "gather":
                self.results[key] = ordered
            elif op == "barrier":
                self.results[key] = True
            del self.rounds[key]
        return True

    def fetch(self, round_key: str):
        key = (round_key,)
        if key in self.results:
            return True, self.results[key]
        return False, None

    def ack_fetched(self, round_key: str, rank: int):
        key = ("ack", round_key)
        acks = self.rounds.setdefault(key, {})
        acks[rank] = True
        if len(acks) == self.world_size:
            self.results.pop((round_key,), None)
            del self.rounds[key]
        return True

    def put_p2p(self, tag: str, payload):
        self.results[("p2p", tag)] = payload
        return True

    def take_p2p(self, tag: str):
        key = ("p2p", tag)
        if key in self.results:
            return True, self.results.pop(key)
        return False, None


class _Group:
    def __init__(self, name: str, world_size: int, rank: int, backend: str,
                 actor, peer_addrs: dict[int, list] | None = None):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.actor = actor
        # rank -> [host, port] of each member's worker RPC server; when
        # present, collectives run over the peer ring instead of the
        # rendezvous actor.
        self.peer_addrs = peer_addrs or {}
        self._seq = 0

    def next_key(self, op: str) -> str:
        self._seq += 1
        return f"{op}:{self._seq}"

    @property
    def ring(self) -> bool:
        return len(self.peer_addrs) == self.world_size and self.world_size > 1


class GroupManager:
    """Per-process registry of joined groups (reference: GroupManager:40)."""

    def __init__(self):
        self.groups: dict[str, _Group] = {}

    def create(self, group_name: str, world_size: int, rank: int,
               backend: str) -> _Group:
        actor = _RendezvousActor.options(
            name=f"collective:{group_name}", get_if_exists=True,
            lifetime="detached").remote(world_size)
        plane = _get_peer_plane()
        ray_tpu.get(actor.join.remote(
            rank, {"backend": backend, "addr": plane.addr}))
        # Wait for full membership.
        deadline = time.monotonic() + 60
        while ray_tpu.get(actor.num_members.remote()) < world_size:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {group_name!r}: only "
                    f"{ray_tpu.get(actor.num_members.remote())}/{world_size} "
                    "members joined within 60s")
            time.sleep(0.02)
        members = ray_tpu.get(actor.members_info.remote())
        peer_addrs = {int(r): m["addr"] for r, m in members.items()
                      if m.get("addr")}
        g = _Group(group_name, world_size, rank, backend, actor, peer_addrs)
        self.groups[group_name] = g
        return g

    def get(self, group_name: str) -> _Group:
        if group_name not in self.groups:
            raise ValueError(
                f"collective group {group_name!r} is not initialized in this "
                "process; call init_collective_group first")
        return self.groups[group_name]

    def destroy(self, group_name: str):
        g = self.groups.pop(group_name, None)
        if g is not None and g.rank == 0:
            try:
                ray_tpu.kill(g.actor)
            except Exception:
                pass


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int, backend: str = "xla",
                          group_name: str = "default") -> None:
    if backend not in ("xla", "cpu", "gloo"):
        raise ValueError(f"backend must be 'xla' or 'cpu', got {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _manager.create(group_name, world_size, rank, backend)


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _manager.groups


def _collect(g: _Group, op: str, array):
    key = g.next_key(op)
    ray_tpu.get(g.actor.contribute.remote(key, op, g.rank, array))
    while True:
        done, result = ray_tpu.get(g.actor.fetch.remote(key))
        if done:
            ray_tpu.get(g.actor.ack_fetched.remote(key, g.rank))
            return result
        time.sleep(0.002)


def _ring_reduce_chunks(g: _Group, arr: np.ndarray, op: str):
    """Ring reduce-scatter over flattened chunks; returns (chunks, seq)
    with this rank holding the FULLY reduced chunk at index
    (rank+1) % W after the W-1 steps."""
    plane = _get_peer_plane()
    W, r = g.world_size, g.rank
    right = g.peer_addrs[(r + 1) % W]
    f = _REDUCE_OPS[op]
    flat = np.ascontiguousarray(arr).ravel()
    chunks = [np.array(c) for c in np.array_split(flat, W)]
    seq = g.next_key("ring")
    for step in range(W - 1):
        send_idx = (r - step) % W
        recv_idx = (r - step - 1) % W
        plane.send(g.name, right, f"{seq}:rs{step}", chunks[send_idx])
        got = plane.recv(g.name, f"{seq}:rs{step}")
        chunks[recv_idx] = f(chunks[recv_idx], got)
    return chunks, seq


def _ring_allreduce(g: _Group, arr: np.ndarray, op: str) -> np.ndarray:
    """Classic two-phase ring: reduce-scatter then allgather. Each member
    moves 2·(W-1)/W of the tensor total, independent of W."""
    plane = _get_peer_plane()
    W, r = g.world_size, g.rank
    right = g.peer_addrs[(r + 1) % W]
    chunks, seq = _ring_reduce_chunks(g, arr, op)
    for step in range(W - 1):
        send_idx = (r + 1 - step) % W
        recv_idx = (r - step) % W
        plane.send(g.name, right, f"{seq}:ag{step}", chunks[send_idx])
        chunks[recv_idx] = plane.recv(g.name, f"{seq}:ag{step}")
    out = np.concatenate(chunks)
    return out.reshape(np.asarray(arr).shape)


def _ring_allgather(g: _Group, arr: np.ndarray) -> list:
    """Each member's array circulates the ring once (W-1 forwards)."""
    plane = _get_peer_plane()
    W, r = g.world_size, g.rank
    right = g.peer_addrs[(r + 1) % W]
    seq = g.next_key("ring")
    out: list = [None] * W
    out[r] = np.asarray(arr)
    carry = out[r]
    for step in range(W - 1):
        plane.send(g.name, right, f"{seq}:ag{step}", carry)
        carry = plane.recv(g.name, f"{seq}:ag{step}")
        out[(r - step - 1) % W] = carry
    return out


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """In-place-style allreduce; returns the reduced array."""
    g = _manager.get(group_name)
    arr = np.asarray(tensor)
    if g.ring:
        out = _ring_allreduce(g, arr, op)
    else:
        out = _collect(g, op, arr)
    try:
        tensor[...] = out
    except (TypeError, ValueError):
        pass
    return out


def allgather(tensor, group_name: str = "default") -> list:
    g = _manager.get(group_name)
    if g.ring:
        return _ring_allgather(g, np.asarray(tensor))
    return _collect(g, "gather", np.asarray(tensor))


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    g = _manager.get(group_name)
    arr = np.asarray(tensor)
    if g.ring:
        # Contract (same as the actor path): rank's shard is
        # array_split(reduced, W)[rank] along AXIS 0 of the original
        # shape. The ring chunks over the ravel, so reconstruct the full
        # reduced array and slice — still O(size) ring traffic with no
        # single-process funnel, just not the reduce-scatter minimum.
        reduced = _ring_allreduce(g, arr, op)
        return np.array_split(reduced, g.world_size)[g.rank]
    reduced = _collect(g, op, arr)
    shards = np.array_split(reduced, g.world_size)
    return shards[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _manager.get(group_name)
    if g.ring:
        plane = _get_peer_plane()
        seq = g.next_key("ring")
        if g.rank == src_rank:
            arr = np.asarray(tensor)
            for dst, addr in g.peer_addrs.items():
                if dst != src_rank:
                    plane.send(g.name, addr, f"{seq}:bc", arr)
            out = arr
        else:
            out = plane.recv(g.name, f"{seq}:bc")
    else:
        gathered = _collect(g, "gather", np.asarray(tensor))
        out = gathered[src_rank]
    try:
        tensor[...] = out
    except (TypeError, ValueError):
        pass
    return out


def barrier(group_name: str = "default") -> None:
    g = _manager.get(group_name)
    _collect(g, "barrier", True)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    g = _manager.get(group_name)
    tag = f"{g.rank}->{dst_rank}:{g.next_key('p2p')}"
    # Tag must be deterministic between the pair: use a pair-scoped counter.
    tag = f"{g.rank}->{dst_rank}"
    ray_tpu.get(g.actor.put_p2p.remote(tag, np.asarray(tensor)))


def recv(tensor, src_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    tag = f"{src_rank}->{g.rank}"
    while True:
        done, payload = ray_tpu.get(g.actor.take_p2p.remote(tag))
        if done:
            try:
                tensor[...] = payload
            except (TypeError, ValueError):
                pass
            return payload
        time.sleep(0.002)
