"""Collective communication API with a TPU-native XLA backend.

Parity: reference python/ray/util/collective/collective.py:120-655
(init_collective_group / allreduce / allgather / reducescatter / broadcast /
send / recv, GroupManager:40). The reference's backends are NCCL (cupy, with
a named-actor KV rendezvous, nccl_collective_group.py:28) and GLOO (pygloo).

TPU-native re-design (SURVEY.md §2.5): there are two planes —

1. the *device plane*: collectives lower to XLA ops compiled INTO the
   program (`jax.lax.psum/all_gather/ppermute/all_to_all` over ICI). Use
   `ray_tpu.util.collective.ops` inside `shard_map`/`pjit` — nothing to
   initialize; the mesh IS the group. This is the architectural difference
   from NCCL to embrace: no runtime library call, the compiler schedules
   communication with compute.

2. the *host plane* (this module's group API): processes (actors) form a
   group by rendezvous through a named actor in the GCS (replacing the
   reference's NCCL-unique-id rendezvous) and run collectives on host
   numpy arrays over the object-store/DCN path. On multi-host TPU pods the
   group init also performs the `jax.distributed.initialize` handshake so
   members can subsequently compile single multi-host XLA programs.
"""

from __future__ import annotations

import time

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda a, b: a + b,
    "product": lambda a, b: a * b,
    "min": np.minimum,
    "max": np.maximum,
}


@ray_tpu.remote
class _RendezvousActor:
    """Coordination point for one collective group (replaces the reference's
    NCCLUniqueIDStore named actor, nccl_collective_group.py:28)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.members: dict[int, dict] = {}
        self.rounds: dict[tuple, dict] = {}
        self.results: dict[tuple, object] = {}

    def join(self, rank: int, info: dict) -> dict:
        self.members[rank] = info
        return {"joined": len(self.members), "world_size": self.world_size}

    def num_members(self) -> int:
        return len(self.members)

    def contribute(self, round_key: str, op: str, rank: int, payload):
        """Gather contributions; when all present, compute + publish."""
        key = (round_key,)
        r = self.rounds.setdefault(key, {})
        r[rank] = payload
        if len(r) == self.world_size:
            ordered = [r[i] for i in range(self.world_size)]
            if op in _REDUCE_OPS:
                acc = ordered[0]
                f = _REDUCE_OPS[op]
                for x in ordered[1:]:
                    acc = f(acc, x)
                self.results[key] = acc
            elif op == "gather":
                self.results[key] = ordered
            elif op == "barrier":
                self.results[key] = True
            del self.rounds[key]
        return True

    def fetch(self, round_key: str):
        key = (round_key,)
        if key in self.results:
            return True, self.results[key]
        return False, None

    def ack_fetched(self, round_key: str, rank: int):
        key = ("ack", round_key)
        acks = self.rounds.setdefault(key, {})
        acks[rank] = True
        if len(acks) == self.world_size:
            self.results.pop((round_key,), None)
            del self.rounds[key]
        return True

    def put_p2p(self, tag: str, payload):
        self.results[("p2p", tag)] = payload
        return True

    def take_p2p(self, tag: str):
        key = ("p2p", tag)
        if key in self.results:
            return True, self.results.pop(key)
        return False, None


class _Group:
    def __init__(self, name: str, world_size: int, rank: int, backend: str,
                 actor):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.actor = actor
        self._seq = 0

    def next_key(self, op: str) -> str:
        self._seq += 1
        return f"{op}:{self._seq}"


class GroupManager:
    """Per-process registry of joined groups (reference: GroupManager:40)."""

    def __init__(self):
        self.groups: dict[str, _Group] = {}

    def create(self, group_name: str, world_size: int, rank: int,
               backend: str) -> _Group:
        actor = _RendezvousActor.options(
            name=f"collective:{group_name}", get_if_exists=True,
            lifetime="detached").remote(world_size)
        ray_tpu.get(actor.join.remote(rank, {"backend": backend}))
        # Wait for full membership.
        deadline = time.monotonic() + 60
        while ray_tpu.get(actor.num_members.remote()) < world_size:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {group_name!r}: only "
                    f"{ray_tpu.get(actor.num_members.remote())}/{world_size} "
                    "members joined within 60s")
            time.sleep(0.02)
        g = _Group(group_name, world_size, rank, backend, actor)
        self.groups[group_name] = g
        return g

    def get(self, group_name: str) -> _Group:
        if group_name not in self.groups:
            raise ValueError(
                f"collective group {group_name!r} is not initialized in this "
                "process; call init_collective_group first")
        return self.groups[group_name]

    def destroy(self, group_name: str):
        g = self.groups.pop(group_name, None)
        if g is not None and g.rank == 0:
            try:
                ray_tpu.kill(g.actor)
            except Exception:
                pass


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int, backend: str = "xla",
                          group_name: str = "default") -> None:
    if backend not in ("xla", "cpu", "gloo"):
        raise ValueError(f"backend must be 'xla' or 'cpu', got {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _manager.create(group_name, world_size, rank, backend)


def destroy_collective_group(group_name: str = "default") -> None:
    _manager.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _manager.groups


def _collect(g: _Group, op: str, array):
    key = g.next_key(op)
    ray_tpu.get(g.actor.contribute.remote(key, op, g.rank, array))
    while True:
        done, result = ray_tpu.get(g.actor.fetch.remote(key))
        if done:
            ray_tpu.get(g.actor.ack_fetched.remote(key, g.rank))
            return result
        time.sleep(0.002)


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """In-place-style allreduce; returns the reduced array."""
    g = _manager.get(group_name)
    arr = np.asarray(tensor)
    out = _collect(g, op, arr)
    try:
        tensor[...] = out
    except (TypeError, ValueError):
        pass
    return out


def allgather(tensor, group_name: str = "default") -> list:
    g = _manager.get(group_name)
    return _collect(g, "gather", np.asarray(tensor))


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    g = _manager.get(group_name)
    arr = np.asarray(tensor)
    reduced = _collect(g, op, arr)
    shards = np.array_split(reduced, g.world_size)
    return shards[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _manager.get(group_name)
    gathered = _collect(g, "gather", np.asarray(tensor) if g.rank == src_rank
                        else np.asarray(tensor))
    out = gathered[src_rank]
    try:
        tensor[...] = out
    except (TypeError, ValueError):
        pass
    return out


def barrier(group_name: str = "default") -> None:
    g = _manager.get(group_name)
    _collect(g, "barrier", True)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    g = _manager.get(group_name)
    tag = f"{g.rank}->{dst_rank}:{g.next_key('p2p')}"
    # Tag must be deterministic between the pair: use a pair-scoped counter.
    tag = f"{g.rank}->{dst_rank}"
    ray_tpu.get(g.actor.put_p2p.remote(tag, np.asarray(tensor)))


def recv(tensor, src_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    tag = f"{src_rank}->{g.rank}"
    while True:
        done, payload = ray_tpu.get(g.actor.take_p2p.remote(tag))
        if done:
            try:
                tensor[...] = payload
            except (TypeError, ValueError):
                pass
            return payload
        time.sleep(0.002)
