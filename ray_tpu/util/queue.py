"""Distributed FIFO queue backed by an actor.

Parity: reference python/ray/util/queue.py (Queue over _QueueActor).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: deque = deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return False, None
        return True, self.items.popleft()

    def get_batch(self, n: int):
        out = []
        while self.items and len(out) < n:
            out.append(self.items.popleft())
        return out


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        cls = _QueueActor
        if actor_options:
            cls = _QueueActor.options(**actor_options)
        self.actor = cls.remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def put(self, item: Any, block: bool = True, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() > deadline:
                raise Full
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(0.01)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def get_batch(self, n: int) -> list:
        return ray_tpu.get(self.actor.get_batch.remote(n))

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
