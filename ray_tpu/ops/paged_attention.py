"""Paged decode attention: single-query attention over a block-table KV
pool (the vLLM "PagedAttention" idea, TPU-shaped).

The KV cache is a shared POOL of fixed-size pages; each sequence owns a
page table of pool indices. HBM is allocated by total resident tokens,
not `max_len x slots` — the round-1 engine's admitted waste
(reference: the reference serves LLMs through vLLM-style external
engines whose core trick is exactly this block table).

The kernel uses Pallas scalar prefetch (PrefetchScalarGridSpec): the
page table rides in SMEM and the grid's index_map dereferences it, so
each grid step DMAs one page of K/V straight from the pool — attention
runs over scattered pages without ever materializing a contiguous
per-sequence cache. Online softmax accumulates across pages (same
recurrence as ops/attention.py's flash kernel).

On CPU (tests) the kernel runs in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30


def _interpret_mode() -> bool:
    return jax.default_backend() != "tpu"


def _online_softmax_update(pi, length, q, k, v, m_prev, l_prev, acc_prev,
                           *, page_size: int, sm_scale: float):
    """One page of the online-softmax recurrence, shared by EVERY paged
    kernel variant (single-sequence, grid-batched, fused-heads) so a
    numerics change cannot silently miss one of them.

    Pure function of values: callers own the scratch-ref IO (the fused
    kernel updates row SLICES of shared scratch). Every dot is a plain
    2D (G, D) x (page, D) matmul: Mosaic lowers 2D dots onto the MXU
    but rejects the batched `hgd,thd` einsum form ("batch dims must be
    equal" on real TPU; caught by scripts/tpu_kernel_sweep.py on-chip
    validation). Returns (m_new, l_new, acc_new).
    """
    # scores[g, t] = q[g, :] . k[t, :]  — 2D dot, MXU-safe
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ()))) * sm_scale
    token_idx = pi * page_size + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    scores = jnp.where(token_idx < length, scores, _NEG_INF)

    m_cur = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)                 # (G, page)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())))  # (G, D)
    return m_new, l_new, acc_prev * alpha + pv


def _normalized(l, acc):
    """Final softmax normalization with the all-masked guard (l == 0)."""
    return acc / jnp.where(l == 0.0, 1.0, l)


def _online_softmax_page_step(pi, num_page_steps, length, q, k, v,
                              o_write, m_scratch, l_scratch, acc_scratch,
                              *, page_size: int, sm_scale: float):
    """One grid step over whole-scratch refs (single-sequence and
    head-on-grid batched kernels). pi: page-step program id; q: (G, D);
    k/v: (page, D); o_write: callback writing the normalized (G, D)
    output on the last step."""
    @pl.when(pi == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    m_new, l_new, acc_new = _online_softmax_update(
        pi, length, q, k, v, m_scratch[...], l_scratch[...],
        acc_scratch[...], page_size=page_size, sm_scale=sm_scale)
    m_scratch[...] = m_new
    l_scratch[...] = l_new
    acc_scratch[...] = acc_new

    @pl.when(pi == num_page_steps - 1)
    def _finish():
        o_write(_normalized(l_scratch[...], acc_scratch[...]))


def _paged_decode_kernel(page_table_ref, length_ref,  # scalar prefetch
                         q_ref, k_ref, v_ref, o_ref,
                         m_scratch, l_scratch, acc_scratch,
                         *, page_size: int, num_pages: int, groups: int,
                         sm_scale: float):
    # Grid: (Hkv, npages)
    pi = pl.program_id(1)

    def write(out):
        o_ref[0] = out.astype(o_ref.dtype)

    _online_softmax_page_step(
        pi, pl.num_programs(1), length_ref[0],
        q_ref[0].astype(jnp.float32),           # (G, D)
        k_ref[0, 0].astype(jnp.float32),        # (page, D)
        v_ref[0, 0].astype(jnp.float32),
        write, m_scratch, l_scratch, acc_scratch,
        page_size=page_size, sm_scale=sm_scale)


def paged_decode_attention(q, k_pool, v_pool, page_table, length,
                           *, sm_scale: float | None = None):
    """Single-token decode attention over paged KV.

    q:          (H, D) query for ONE sequence's current token
    k_pool/v_pool: (P, Hkv, page_size, D) shared pools — head-then-page
                minor layout so each (head, page) block is a contiguous
                (page, D) tile (Mosaic requires the last two block dims
                to tile as (sublane, lane))
    page_table: (NP,) int32 pool indices owned by this sequence (entries
                past the live length may be arbitrary valid indices)
    length:     () int32 valid token count (incl. the current token,
                whose K/V must already be written to the pool)
    Returns (H, D). vmap over sequences for a batch.
    """
    H, D = q.shape
    P, Hkv, page_size, _ = k_pool.shape
    groups = H // Hkv
    npages = page_table.shape[0]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)

    q3 = q.reshape(Hkv, groups, D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Hkv, npages),
        in_specs=[
            pl.BlockSpec((1, groups, D), lambda h, i, pt, ln: (h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, D),
                         lambda h, i, pt, ln: (pt[i], h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, D),
                         lambda h, i, pt, ln: (pt[i], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, groups, D),
                               lambda h, i, pt, ln: (h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((groups, 1), jnp.float32),
            pltpu.VMEM((groups, 1), jnp.float32),
            pltpu.VMEM((groups, D), jnp.float32),
        ],
    ) if pltpu else None
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page_size=page_size,
                          num_pages=npages, groups=groups,
                          sm_scale=sm_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hkv, groups, D), q.dtype),
        interpret=_interpret_mode(),
    )(page_table.astype(jnp.int32), length.reshape(1).astype(jnp.int32),
      q3, k_pool, v_pool)
    return out.reshape(H, D)


def _paged_decode_batch_kernel(page_table_ref, length_ref,  # scalar prefetch
                               q_ref, k_ref, v_ref, o_ref,
                               m_scratch, l_scratch, acc_scratch,
                               *, page_size: int, sm_scale: float):
    # Grid: (B, Hkv, npages); pages iterate fastest, so per-(b, h)
    # scratch resets at pi == 0 and writes back on the last page step.
    b = pl.program_id(0)
    pi = pl.program_id(2)

    def write(out):
        o_ref[0, 0] = out.astype(o_ref.dtype)

    _online_softmax_page_step(
        pi, pl.num_programs(2), length_ref[b],
        q_ref[0, 0].astype(jnp.float32),        # (G, D)
        k_ref[0, 0].astype(jnp.float32),        # (page, D)
        v_ref[0, 0].astype(jnp.float32),
        write, m_scratch, l_scratch, acc_scratch,
        page_size=page_size, sm_scale=sm_scale)


def _paged_decode_batch_fused_kernel(page_table_ref, length_ref,  # prefetch
                                     q_ref, k_ref, v_ref, o_ref,
                                     m_scratch, l_scratch, acc_scratch,
                                     *, page_size: int, num_heads: int,
                                     groups: int, sm_scale: float):
    # Grid: (B, npages) — each step DMAs a FULL pool page (all Hkv heads
    # contiguous in the (P, Hkv, page, D) layout) and unrolls a static
    # per-head loop of 2D dots. Hkv-times fewer grid steps and
    # Hkv-times larger transfers than the head-on-grid variant: this
    # kernel is DMA-bound, so transfer size sets throughput.
    b = pl.program_id(0)
    pi = pl.program_id(1)
    length = length_ref[b]

    @pl.when(pi == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    for h in range(num_heads):      # static: unrolled at trace time
        rows = slice(h * groups, (h + 1) * groups)
        m_new, l_new, acc_new = _online_softmax_update(
            pi, length,
            q_ref[0, h].astype(jnp.float32),       # (G, D)
            k_ref[0, h].astype(jnp.float32),       # (page, D)
            v_ref[0, h].astype(jnp.float32),
            m_scratch[rows], l_scratch[rows], acc_scratch[rows],
            page_size=page_size, sm_scale=sm_scale)
        m_scratch[rows] = m_new
        l_scratch[rows] = l_new
        acc_scratch[rows] = acc_new

    @pl.when(pi == pl.num_programs(1) - 1)
    def _finish():
        o_ref[0] = _normalized(l_scratch[...],
                               acc_scratch[...]).astype(o_ref.dtype)


def paged_decode_attention_batch(q, k_pool, v_pool, page_tables, lengths,
                                 *, sm_scale: float | None = None,
                                 fused_heads: bool = False):
    """Batched single-token decode attention over paged KV.

    The batch dimension is a leading GRID axis (not vmap — scalar-prefetch
    pallas calls don't batch), so one compiled program serves every slot
    of a continuous-batching engine per decode step.

    q:           (B, H, D) one query per sequence
    k/v_pool:    (P, Hkv, page_size, D) pools SHARED by all sequences
                 (head-then-page minor layout; see paged_decode_attention)
    page_tables: (B, NP) int32 pool indices per sequence
    lengths:     (B,) int32 valid token counts (incl. current tokens)
    fused_heads: one grid step per (sequence, page) covering ALL KV
                 heads (full-page contiguous DMA, Hkv-times fewer grid
                 steps) vs one per (sequence, head, page). Default stays
                 False until the fused variant passes on-chip Mosaic
                 validation (scripts/tpu_kernel_sweep.py) — interpret
                 mode has accepted kernels real TPU rejects before.
    Returns (B, H, D).
    """
    B, H, D = q.shape
    P, Hkv, page_size, _ = k_pool.shape
    groups = H // Hkv
    npages = page_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)

    q4 = q.reshape(B, Hkv, groups, D)
    if fused_heads:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, npages),
            in_specs=[
                pl.BlockSpec((1, Hkv, groups, D),
                             lambda b, i, pt, ln: (b, 0, 0, 0)),
                pl.BlockSpec((1, Hkv, page_size, D),
                             lambda b, i, pt, ln: (pt[b, i], 0, 0, 0)),
                pl.BlockSpec((1, Hkv, page_size, D),
                             lambda b, i, pt, ln: (pt[b, i], 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, Hkv * groups, D),
                                   lambda b, i, pt, ln: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Hkv * groups, 1), jnp.float32),
                pltpu.VMEM((Hkv * groups, 1), jnp.float32),
                pltpu.VMEM((Hkv * groups, D), jnp.float32),
            ],
        ) if pltpu else None
        out = pl.pallas_call(
            functools.partial(_paged_decode_batch_fused_kernel,
                              page_size=page_size, num_heads=Hkv,
                              groups=groups, sm_scale=sm_scale),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, Hkv * groups, D), q.dtype),
            interpret=_interpret_mode(),
        )(page_tables.astype(jnp.int32), lengths.astype(jnp.int32),
          q4, k_pool, v_pool)
        return out.reshape(B, H, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, npages),
        in_specs=[
            pl.BlockSpec((1, 1, groups, D),
                         lambda b, h, i, pt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, D),
                         lambda b, h, i, pt, ln: (pt[b, i], h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, D),
                         lambda b, h, i, pt, ln: (pt[b, i], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, groups, D),
                               lambda b, h, i, pt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((groups, 1), jnp.float32),
            pltpu.VMEM((groups, 1), jnp.float32),
            pltpu.VMEM((groups, D), jnp.float32),
        ],
    ) if pltpu else None
    out = pl.pallas_call(
        functools.partial(_paged_decode_batch_kernel, page_size=page_size,
                          sm_scale=sm_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, groups, D), q.dtype),
        interpret=_interpret_mode(),
    )(page_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q4, k_pool, v_pool)
    return out.reshape(B, H, D)


class PageAllocator:
    """Host-side free-list allocator for KV pool pages (one per engine).

    Parity target: vLLM's block manager — sequences grow page by page;
    freeing a sequence returns its pages to the pool. Pure Python (the
    allocator runs in the serving loop, not inside jit)."""

    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self._free = list(range(num_pages - 1, -1, -1))
        self._owned: dict[str, list[int]] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def allocate(self, seq_id: str, num_tokens: int) -> list[int]:
        """Reserve pages so `seq_id` can hold num_tokens total; grows the
        existing reservation. Raises MemoryError when the pool is dry
        (callers queue the request — admission control)."""
        owned = self._owned.setdefault(seq_id, [])
        need = self.pages_needed(num_tokens) - len(owned)
        if need > len(self._free):
            raise MemoryError(
                f"KV pool exhausted: need {need} pages, {len(self._free)} free")
        for _ in range(max(0, need)):
            owned.append(self._free.pop())
        return list(owned)

    def table(self, seq_id: str, npages: int) -> "jnp.ndarray":
        """Fixed-width page table (padded with a valid dummy index so the
        kernel's out-of-range grid steps stay in bounds; masking by
        `length` makes their scores irrelevant)."""
        owned = self._owned.get(seq_id, [])
        pad = owned[-1] if owned else 0
        rows = (owned + [pad] * npages)[:npages]
        return jnp.asarray(rows, jnp.int32)

    def free(self, seq_id: str) -> None:
        self._free.extend(reversed(self._owned.pop(seq_id, [])))
