"""Attention kernels: Pallas flash attention + ring attention.

The reference ships NO attention kernels — its compute plane is torch
(SURVEY.md §2.4: sequence/context parallelism "absent in reference"; §5
names Pallas ring/flash attention as the rebuild's native additions).

- `flash_attention`: TPU Pallas kernel, online-softmax forward with the
  canonical (batch, heads, q-block, k-block) grid; k is the innermost
  sequential grid dimension so VMEM scratch accumulators persist across k
  steps. Backward is a blockwise lax.scan recomputation using the saved
  logsumexp (memory O(S·block) not O(S²)).
- `ring_attention`: sequence-parallel attention inside `shard_map` — each
  device holds a sequence shard of Q/K/V; K/V shards rotate around the mesh
  axis via `lax.ppermute` while a running (out, max, denom) merge keeps
  exact softmax semantics. Communication rides ICI and overlaps with the
  per-step flash computation.

On CPU (tests) the Pallas kernel runs in interpret mode.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ray_tpu.util.collective.ops import axis_size as _axis_size

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30


def _interpret_mode() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Pallas flash attention (forward)
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_scratch, l_scratch, acc_scratch,
                      *, sm_scale: float, causal: bool,
                      block_q: int, block_k: int, num_k_blocks: int,
                      kv_valid_len: int | None = None):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, _NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # Causal: blocks strictly above the diagonal are fully masked — skip
    # their compute entirely (the index map also clamps their DMAs onto
    # the diagonal block, so skipped steps copy nothing new).  This halves
    # causal attention FLOPs, like the canonical TPU flash kernel.
    needed = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(needed)
    def _compute():
        # Keep q/k/v in their storage dtype (bf16 on TPU): the MXU runs
        # bf16×bf16→f32 at full rate; upcasting inputs to f32 first would
        # halve matmul throughput. Accumulation is f32 via
        # preferred_element_type.
        q = q_ref[0, 0]                                # (block_q, d)
        k = k_ref[0, 0]                                # (block_k, d)
        v = v_ref[0, 0]                                # (block_k, d)

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale

        if causal:
            # Only diagonal-straddling blocks need the mask; interior
            # blocks (block fully below diagonal) skip it.
            q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if kv_valid_len is not None and \
                kv_valid_len < num_k_blocks * block_k:
            # Sequence padded up to a block multiple: keys at or beyond
            # kv_valid_len are invisible.  (Static shapes — the mask is an
            # elementwise where; interior blocks pass through unchanged.)
            k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos < kv_valid_len, s, _NEG_INF)

        m_prev = m_scratch[:]                        # (block_q, 1)
        l_prev = l_scratch[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Guard fully-masked rows (m_new == -inf) against NaNs.
        m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(jnp.where(s <= _NEG_INF / 2, -jnp.inf, s - m_safe))
        alpha = jnp.exp(jnp.where(m_prev <= _NEG_INF / 2, -jnp.inf,
                                  m_prev - m_safe))
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        l = l_scratch[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        m = m_scratch[:]
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        lse_ref[0, 0] = lse.astype(jnp.float32)


def _vma_supported() -> bool:
    """Feature-detect ShapeDtypeStruct(vma=...) + jax.typeof: both arrived
    together; on older JAX we skip vma (matching the lax.pvary fallback
    path used by ring attention below)."""
    global _VMA_OK
    if _VMA_OK is None:
        try:
            jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
            _VMA_OK = hasattr(jax, "typeof")
        except TypeError:
            _VMA_OK = False
    return _VMA_OK


_VMA_OK = None


def _operand_vma(*arrays) -> frozenset:
    """Union of mesh axes the operands vary over (empty outside shard_map)."""
    vma: frozenset = frozenset()
    if not _vma_supported():
        return vma
    for a in arrays:
        t = jax.typeof(a)
        vma = vma | getattr(t, "vma", frozenset())
    return vma


def _out_struct(shape, dtype, vma):
    """ShapeDtypeStruct with vma when this JAX supports it."""
    if _vma_supported():
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_forward(q, k, v, sm_scale: float, causal: bool,
                   block_q: int, block_k: int,
                   kv_valid_len: int | None = None):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]

    def fit_block(block, seq):
        # Largest block ≤ requested that divides the sequence (halving
        # first — stays MXU-aligned for the common power-of-two seqs —
        # then any divisor; a prime length degrades to one block).
        block = min(block, seq)
        while block > 1 and seq % block:
            block //= 2
        if seq % block:
            block = seq
        return block

    block_q = fit_block(block_q, Sq)
    block_k = fit_block(block_k, Sk)
    grid = (B, H, Sq // block_q, Sk // block_k)

    if causal:
        # Clamp skipped (above-diagonal) blocks onto the diagonal: Pallas
        # elides the DMA when the block index repeats, so skipped grid
        # steps move no data.
        def kv_index(b, h, qi, ki):
            last = (qi * block_q + block_q - 1) // block_k
            return (b, h, jnp.minimum(ki, last), 0)
    else:
        def kv_index(b, h, qi, ki):
            return (b, h, ki, 0)

    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          num_k_blocks=Sk // block_k,
                          kv_valid_len=kv_valid_len),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D), kv_index),
            pl.BlockSpec((1, 1, block_k, D), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            # vma: under shard_map (ring/Ulysses wrappers) outputs vary
            # over the same mesh axes as the operands; required when the
            # kernel is called with check_vma=True (the default).
            _out_struct((B, H, Sq, D), q.dtype, _operand_vma(q, k, v)),
            _out_struct((B, H, Sq, 1), jnp.float32, _operand_vma(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32) if pltpu else None,
            pltpu.VMEM((block_q, 1), jnp.float32) if pltpu else None,
            pltpu.VMEM((block_q, D), jnp.float32) if pltpu else None,
        ] if pltpu else [],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ) if (pltpu and not _interpret_mode()) else None,
        interpret=_interpret_mode(),
    )(q, k, v)
    return out, lse.reshape(B, H, Sq)



# ---------------------------------------------------------------------------
# Backward: blockwise recomputation with saved logsumexp
# ---------------------------------------------------------------------------


def _flash_backward(sm_scale, causal, block_q, block_k, kv_valid_len, res, do):
    # Operands stay in their storage dtype (bf16 on TPU — full-rate MXU);
    # every einsum accumulates in f32 via preferred_element_type, and the
    # dk/dv accumulators are f32.
    q, k, v, out, lse = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    f32 = functools.partial(jnp.einsum, preferred_element_type=jnp.float32)
    delta = f32("bhsd,bhsd->bhs", out, do)                   # (B,H,Sq)

    bq = min(block_q, Sq)
    if Sq % bq:
        bq = Sq

    def p_block(qi_start, q_blk, lse_blk):
        s = f32("bhqd,bhkd->bhqk", q_blk, k) * sm_scale
        if causal:
            q_pos = qi_start + jnp.arange(q_blk.shape[2])[:, None]
            k_pos = jnp.arange(Sk)[None, :]
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if kv_valid_len is not None and kv_valid_len < Sk:
            # Same padded-key mask as the forward: without it the
            # recomputed p would leak gradient into padding keys.
            s = jnp.where(jnp.arange(Sk)[None, :] < kv_valid_len, s,
                          _NEG_INF)
        return jnp.exp(s - lse_blk[..., None])

    def scan_body(carry, idx):
        dk_acc, dv_acc = carry
        qs = idx * bq
        q_blk = lax.dynamic_slice_in_dim(q, qs, bq, axis=2)
        do_blk = lax.dynamic_slice_in_dim(do, qs, bq, axis=2)
        lse_blk = lax.dynamic_slice_in_dim(lse, qs, bq, axis=2)
        dl_blk = lax.dynamic_slice_in_dim(delta, qs, bq, axis=2)
        p = p_block(qs, q_blk, lse_blk)                      # (B,H,bq,Sk) f32
        pb = p.astype(v.dtype)
        dv_acc = dv_acc + f32("bhqk,bhqd->bhkd", pb, do_blk)
        dp = f32("bhqd,bhkd->bhqk", do_blk, v)
        ds = (p * (dp - dl_blk[..., None]) * sm_scale).astype(v.dtype)
        dq_blk = f32("bhqk,bhkd->bhqd", ds, k)
        dk_acc = dk_acc + f32("bhqk,bhqd->bhkd", ds, q_blk)
        return (dk_acc, dv_acc), dq_blk

    init = (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32))
    (dk, dv), dq_blocks = lax.scan(scan_body, init, jnp.arange(Sq // bq))
    # dq_blocks: (nq, B, H, bq, D) → (B, H, Sq, D)
    dq = jnp.moveaxis(dq_blocks, 0, 2).reshape(B, H, Sq, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, sm_scale: float | None = None,
                    causal: bool = False, block_q: int = 512,
                    block_k: int = 512):
    """Flash attention. q,k,v: (batch, heads, seq, head_dim).

    Default (block_q, block_k) = (512, 512): chosen by IN-MODEL A/B on
    a real v5e chip (1.2B decoder bench, B2 S2048): 249.6-250.1 ms/step
    vs 254.1-254.3 for (1024, 1024), reproducibly — even though the
    standalone kernel sweep (scripts/tpu_kernel_sweep.py) ranks 1024^2
    faster in isolation (7.18 vs 11.16 ms fwd+bwd). Trust end-to-end
    timings over microbenchmarks here; re-sweep in-model if the
    flagship shape changes. Blocks are clamped to the sequence length
    for shorter inputs.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k)
    return out


def _fa_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(sm_scale, causal, block_q, block_k, res, do):
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(res[0].shape[-1])
    return _flash_backward(scale, causal, block_q, block_k, None, res, do)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def mha_reference(q, k, v, sm_scale: float | None = None, causal: bool = False):
    """Plain jnp attention for correctness checks."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones(s.shape[-2:], dtype=bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism)
# ---------------------------------------------------------------------------


def ring_attention(q, k, v, axis: str = "sp", *, causal: bool = False,
                   sm_scale: float | None = None):
    """Exact attention over a sequence sharded on a mesh axis.

    Call inside shard_map with q,k,v sequence-sharded on `axis`
    (shape per device: (B, H, S/n, D)). K/V rotate n-1 times around the
    ring via ppermute; a running online-softmax merge keeps exactness.
    For causal masking, chunk index determines global positions.
    """
    n = _axis_size(axis)
    my_idx = lax.axis_index(axis)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    B, H, S, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32)

    def body(i, carry):
        """Online-softmax accumulation: acc = Σ exp(s−m)·v, l = Σ exp(s−m)."""
        k_cur, v_cur, acc, m_run, l_run = carry
        k_idx = (my_idx - i) % n  # which global chunk we currently hold
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k_cur.astype(jnp.float32)) * scale
        if causal:
            q_pos = my_idx * S + jnp.arange(S)[:, None]
            k_pos = k_idx * S + jnp.arange(S)[None, :]
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_run, m_cur)
        m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(jnp.where(s <= _NEG_INF / 2, -jnp.inf, s - m_safe))
        alpha = jnp.exp(jnp.where(m_run <= _NEG_INF / 2, -jnp.inf,
                                  m_run - m_safe))
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       v_cur.astype(jnp.float32))
        l_run = l_run * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # Rotate k/v around the ring (result unused on the last step).
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return k_nxt, v_nxt, acc, m_new, l_run

    # Mark the carries as varying over the ring axis so the scan carry
    # types match (shard_map's varying-axis type system). pcast is the
    # current spelling; fall back to pvary on older JAX.
    if hasattr(lax, "pcast"):
        _vary = lambda x: lax.pcast(x, axis, to="varying")  # noqa: E731
    elif hasattr(lax, "pvary"):
        _vary = lambda x: lax.pvary(x, (axis,))  # noqa: E731
    else:
        # jax 0.4.x: shard_map has no varying-axis type system yet —
        # no cast needed.
        _vary = lambda x: x  # noqa: E731
    acc0 = _vary(jnp.zeros((B, H, S, D), jnp.float32))
    m0 = _vary(jnp.full((B, H, S, 1), _NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, S, 1), jnp.float32))
    _, _, acc, _, l = lax.fori_loop(0, n, body, (k, v, acc0, m0, l0))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses attention (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------


def ulysses_attention(q, k, v, axis: str = "sp", *, causal: bool = False,
                      sm_scale: float | None = None):
    """DeepSpeed-Ulysses-style sequence parallelism inside shard_map.

    Inputs are sequence-sharded on `axis`: per-device (B, H, S/n, D).
    One all-to-all re-shards sequence→heads: (B, H/n, S, D) — each device
    then holds the FULL sequence for H/n heads and runs ordinary (flash)
    attention locally; a second all-to-all restores sequence sharding.
    Two all-to-alls ride ICI vs ring attention's n-1 ppermute hops —
    better when H ≥ n and the sequence fits per-device after head split.

    The reference has no sequence parallelism at all (SURVEY.md §2.4: SP
    "absent", Ulysses named as the rebuild deliverable).
    """
    n = _axis_size(axis)
    B, H, S, D = q.shape  # S = local shard of the sequence
    if H % n:
        raise ValueError(f"ulysses needs heads ({H}) divisible by axis ({n})")

    def seq_to_heads(x):
        # (B, H, S_local, D) -> (B, H/n, S_full, D): head dim scatters
        # across devices, sequence chunks gather in device order.
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        # (B, H/n, S_full, D) -> (B, H, S_local, D): inverse exchange.
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if _interpret_mode():
        out = mha_reference(qh, kh, vh, sm_scale, causal)
    else:
        out = flash_attention(qh, kh, vh, sm_scale, causal)
    return heads_to_seq(out.astype(q.dtype))
