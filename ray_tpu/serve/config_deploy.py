"""Declarative Serve deployment from a config dict/file (reference:
python/ray/serve/schema.py ServeDeploySchema + the REST config the
dashboard serve module and `serve deploy` CLI consume).

Config shape::

    {"applications": [{
        "name": "app1",                       # optional
        "import_path": "my_module:app",       # a BOUND Deployment
        "route_prefix": "/app1",              # optional
        "deployments": [{                     # optional per-deployment
            "name": "Model",                  #   overrides by name
            "num_replicas": 4,
            "user_config": {...},
            "autoscaling_config": {...},
        }],
    }]}

`deploy_config(cfg)` imports each application's bound Deployment, applies
the overrides, and serve.run()s it; `status()` reports what's running.
"""

from __future__ import annotations

import importlib
import json
from typing import Any

from ray_tpu.serve.deployment import AutoscalingConfig, Deployment


def _import_app(path: str) -> Deployment:
    if ":" in path:
        mod_name, attr = path.split(":", 1)
    else:
        mod_name, _, attr = path.rpartition(".")
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not isinstance(obj, Deployment):
        raise TypeError(f"{path!r} resolved to {type(obj).__name__}, "
                        "expected a bound Deployment")
    return obj


def _graph_names(dep: Deployment) -> set[str]:
    names = {dep.name}
    for a in list(dep._init_args) + list(dep._init_kwargs.values()):
        if isinstance(a, Deployment):
            names |= _graph_names(a)
    return names


# Per-deployment keys a config may override (DeploymentConfig fields).
_OVERRIDE_KEYS = {"num_replicas", "ray_actor_options", "autoscaling_config",
                  "max_ongoing_requests", "user_config"}


def _apply_overrides(app: Deployment, overrides: list[dict]) -> Deployment:
    """Per-deployment config overrides, applied through the whole bound
    graph (children live in init args). Unknown deployment names or
    config keys are ERRORS — a typo must not silently deploy defaults."""
    names = _graph_names(app)
    by_name = {}
    for o in overrides:
        if "name" not in o:
            raise ValueError(f"deployment override missing 'name': {o}")
        if o["name"] not in names:
            raise ValueError(
                f"override for unknown deployment {o['name']!r}; "
                f"this application has {sorted(names)}")
        bad = set(o) - _OVERRIDE_KEYS - {"name"}
        if bad:
            raise ValueError(
                f"unknown config keys for deployment {o['name']!r}: "
                f"{sorted(bad)}; valid: {sorted(_OVERRIDE_KEYS)}")
        by_name[o["name"]] = o

    def rewrite(dep: Deployment) -> Deployment:
        new_args = tuple(rewrite(a) if isinstance(a, Deployment) else a
                         for a in dep._init_args)
        new_kwargs = {k: rewrite(v) if isinstance(v, Deployment) else v
                      for k, v in dep._init_kwargs.items()}
        out = Deployment(dep._target, dep._config, new_args, new_kwargs)
        o = by_name.get(dep.name)
        if o:
            opts = {k: v for k, v in o.items() if k != "name"}
            if isinstance(opts.get("autoscaling_config"), dict):
                opts["autoscaling_config"] = AutoscalingConfig(
                    **opts["autoscaling_config"])
            out = out.options(**opts)
        return out

    return rewrite(app)


def deploy_config(config: dict | str, *, prune: bool = True) -> dict:
    """Apply the config as the GOAL STATE (reference: serve deploy):
    every listed application deploys, and (with prune=True) deployments
    not in any listed application are deleted. Returns {app_name: handle}.

    `config` may be a dict, a JSON object string, or a path to a JSON
    file (anything not starting with '{'/'[')."""
    from ray_tpu import serve

    if isinstance(config, str):
        if config.lstrip().startswith(("{", "[")):
            config = json.loads(config)
        else:
            with open(config) as f:  # missing file -> FileNotFoundError
                config = json.load(f)
    # Phase 1 — resolve and validate EVERY app before touching the
    # cluster, so one bad import_path cannot leave a half-applied config.
    resolved = []
    for i, app in enumerate(config.get("applications", [])):
        dep = _import_app(app["import_path"])
        dep = _apply_overrides(dep, app.get("deployments", []))
        resolved.append((app.get("name") or f"app{i}",
                         dep, app.get("route_prefix")))
    # Phase 2 — deploy.
    handles = {}
    wanted: set[str] = set()
    for name, dep, route_prefix in resolved:
        handles[name] = serve.run(dep, route_prefix=route_prefix)
        wanted |= _graph_names(dep)
    # Phase 3 — prune deployments absent from the goal state.
    if prune:
        for existing in list(serve.status()):
            if existing not in wanted:
                serve.delete(existing)
    return handles
