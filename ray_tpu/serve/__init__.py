"""ray_tpu.serve: model serving with autoscaling replicas.

Parity: reference python/ray/serve (serve.run api.py:465, @serve.deployment
:258, controller, handles, batching, HTTP proxy). `serve.run` deploys onto
the cluster's detached ServeController; handles route with
power-of-two-choices; `start_http_proxy` exposes deployments over HTTP.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import (
    AutoscalingConfig,
    Deployment,
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
    deployment,
    get_multiplexed_model_id,
    multiplexed,
)

_proxy_server = None


def _get_controller():
    return ServeController.options(
        name=CONTROLLER_NAME, get_if_exists=True, lifetime="detached",
        namespace="serve").remote()


def run(target: Deployment, *, name: str | None = None,
        route_prefix: str | None = None) -> DeploymentHandle:
    """Deploy and return a handle (parity: serve.run api.py:465).

    Deployment-graph composition (parity: python/ray/dag +
    deployment_graph_build.py): bound Deployments appearing in another
    deployment's init args deploy first and arrive as DeploymentHandles —
    `serve.run(Ensemble.bind(ModelA.bind(), ModelB.bind()))` gives the
    Ensemble replicas live handles to A and B.
    """
    controller = _get_controller()
    return _deploy_tree(target, controller, route_prefix)


def _deploy_tree(target: Deployment, controller,
                 route_prefix: str | None = None) -> DeploymentHandle:
    def resolve(a):
        if isinstance(a, Deployment):
            return _deploy_tree(a, controller)  # children get no route
        return a

    init_args = tuple(resolve(a) for a in target._init_args)
    init_kwargs = {k: resolve(v) for k, v in target._init_kwargs.items()}
    cfg = target._config
    asc = None
    if cfg.autoscaling_config is not None:
        asc = dict(cfg.autoscaling_config.__dict__)
    ray_tpu.get(controller.deploy.remote(
        cfg.name,
        serialization.dumps_func(target._target),
        serialization.dumps_func((init_args, init_kwargs)),
        cfg.num_replicas,
        cfg.ray_actor_options,
        asc,
        serialization.dumps_func(cfg.user_config)
        if cfg.user_config is not None else None,
        route_prefix,
    ))
    return DeploymentHandle(cfg.name, controller)


def get_deployment_handle(name: str, *_a, **_k) -> DeploymentHandle:
    return DeploymentHandle(name, _get_controller())


def status() -> dict:
    # Read-only: must not spawn a detached controller as a side effect on
    # clusters where serve was never started.
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace="serve")
    except ValueError:
        return {}
    return ray_tpu.get(controller.list_deployments.remote())


def delete(name: str) -> None:
    ray_tpu.get(_get_controller().delete_deployment.remote(name))


def shutdown() -> None:
    global _proxy_server
    _ProxyHandler._route_poll_stop.set()
    _ProxyHandler._route_poll_started = False
    _ProxyHandler._routes = {}
    _ProxyHandler._routes_ts = 0.0
    if _proxy_server is not None:
        _proxy_server.shutdown()
        _proxy_server = None
    for actor, _host, _port in _node_proxies.values():
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass
    _node_proxies.clear()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME, namespace="serve")
        ray_tpu.get(controller.shutdown.remote())
        ray_tpu.kill(controller)
    except Exception:
        pass


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """@serve.batch marker (parity: serve/batching.py). Attach batching
    metadata; the handle batches calls into list-of-inputs invocations."""

    def wrap(fn):
        fn._serve_batch = (max_batch_size, batch_wait_timeout_s)
        return fn

    if _fn is not None:
        return wrap(_fn)
    return wrap


class _ProxyHandler(BaseHTTPRequestHandler):
    # Chunked transfer (streaming) is an HTTP/1.1 construct; the stdlib
    # default of HTTP/1.0 would make strict clients read the chunk framing
    # as body bytes.
    protocol_version = "HTTP/1.1"
    handles: dict[str, DeploymentHandle] = {}
    # Route table {prefix: deployment}: pushed by the controller over a
    # held long-poll connection (reference: proxies subscribe to route
    # updates via LongPollClient, long_poll.py:172); a slow TTL pull
    # remains as the bootstrap/fallback path.
    _routes: dict[str, str] = {}
    _routes_ts: float = 0.0
    _ROUTE_TTL = 10.0
    _route_poll_started = False
    _route_poll_stop = threading.Event()
    _route_poll_version = 0

    def log_message(self, *args):  # silence
        pass

    @classmethod
    def _start_route_poll(cls):
        if cls._route_poll_started:
            return
        cls._route_poll_started = True
        # Fresh Event per poll thread: clearing the shared one would
        # resurrect a previous thread still parked in its (up to 30s)
        # blocking get from before shutdown(), leaving two route-poll
        # threads racing against the new serve session.
        stop = threading.Event()
        cls._route_poll_stop = stop

        def loop():
            import time as _time

            while not stop.is_set():
                t0 = _time.monotonic()
                try:
                    # Look up the EXISTING controller only — get_if_exists
                    # creation here would resurrect a detached controller
                    # after serve.shutdown().
                    controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                                   namespace="serve")
                    upd = ray_tpu.get(
                        controller.long_poll.remote(
                            {"routes": cls._route_poll_version}, 10.0),
                        timeout=30)
                except Exception:
                    if stop.wait(1.0):
                        return
                    continue
                if "routes" not in upd and _time.monotonic() - t0 < 1.0:
                    # Instant empty reply: controller's parked-poll slots
                    # exhausted — back off instead of spinning.
                    if stop.wait(0.5):
                        return
                if "routes" in upd:
                    cls._route_poll_version, cls._routes = upd["routes"]
                    cls._routes_ts = _time.monotonic()

        threading.Thread(target=loop, daemon=True,
                         name="proxy-route-poll").start()

    @classmethod
    def _route_table(cls) -> dict[str, str]:
        import time as _time

        cls._start_route_poll()
        now = _time.monotonic()
        if now - cls._routes_ts > cls._ROUTE_TTL:
            try:
                cls._routes = ray_tpu.get(
                    _get_controller().route_table.remote(), timeout=10)
                cls._routes_ts = now
            except Exception:
                pass
        return cls._routes

    def do_POST(self):
        # Route by longest matching route_prefix (reference: proxy_router);
        # falls back to /<deployment-name>.
        path, _, query = self.path.partition("?")
        name = None
        best_len = -1
        for prefix, dep in self._route_table().items():
            if (path == prefix or path.startswith(prefix.rstrip("/") + "/")
                    or prefix == "/") and len(prefix) > best_len:
                name, best_len = dep, len(prefix)
        if name is None:
            name = path.strip("/").split("/")[0]
        handle = self.handles.get(name)
        if handle is None:
            handle = self.handles[name] = get_deployment_handle(name)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b"{}"
        if "stream=1" in query:
            return self._respond_stream(handle, body)
        try:
            payload = json.loads(body) if body else {}
            result = handle.remote(payload).result(timeout=60)
            data = json.dumps({"result": result}).encode()
            self.send_response(200)
        except Exception as e:  # noqa: BLE001
            data = json.dumps({"error": str(e)}).encode()
            self.send_response(500)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _respond_stream(self, handle, body: bytes):
        """Chunked transfer for generator deployments (?stream=1): one JSON
        line per yielded chunk (reference: serve StreamingResponse over the
        uvicorn proxy)."""
        gen = None
        started = False
        try:
            payload = json.loads(body) if body else {}
            gen = handle.options(stream=True).remote(payload)
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonl")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            started = True
            for chunk in gen:
                line = (json.dumps({"chunk": chunk}) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")
        except Exception as e:  # noqa: BLE001
            if started:
                # Headers + chunks already on the wire: a 500 here would
                # inject a status line mid-body. Drop the connection so the
                # client sees a truncated (unterminated) chunked stream.
                self.close_connection = True
            else:
                try:
                    data = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except Exception:
                    pass
        finally:
            # Client disconnect / handler error mid-stream: release the
            # replica-side generator and the router's outstanding count.
            if gen is not None:
                gen.cancel()

    do_GET = do_POST


@ray_tpu.remote(num_cpus=0)
class _ProxyActor:
    """Runs BOTH ingress protocols inside a worker on a specific node —
    HTTP and the binary msgpack-RPC ingress (reference: serve proxies
    serve HTTP and gRPC on every node, serve/_private/proxy.py:13-38;
    handles inside the actor route to replicas cluster-wide)."""

    def __init__(self, port: int):
        from ray_tpu import serve as _serve

        self.port = _serve.start_http_proxy(host="0.0.0.0", port=port)
        self.rpc_port = _serve.start_rpc_proxy(host="0.0.0.0", port=0)

    def address(self) -> int:
        return self.port

    def rpc_address(self) -> int:
        return self.rpc_port

    def healthy(self) -> bool:
        return True


_node_proxies: dict = {}  # node_id -> (actor, host, port)


def start_proxies(port: int = 0) -> dict:
    """One HTTP proxy per alive node (reference: proxies on every node,
    serve/_private/proxy.py + proxy_state). Idempotent reconcile: calling
    again keeps healthy proxies, replaces dead ones, and covers nodes
    added since. Returns {node_id: (host, port)}. port=0 picks an
    ephemeral port per node — required when several raylets share a host
    (fake multi-node)."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    out = {}
    pending = {}
    for n in ray_tpu.nodes():
        if not n.get("alive"):
            continue
        nid = n["node_id"]
        existing = _node_proxies.get(nid)
        if existing is not None:
            actor, host, known_port = existing
            try:
                if ray_tpu.get(actor.healthy.remote(), timeout=15):
                    if known_port is None:
                        # A previous address fetch failed; re-fetch
                        # rather than cache a useless None port forever.
                        known_port = ray_tpu.get(actor.address.remote(),
                                                 timeout=30)
                        _node_proxies[nid] = (actor, host, known_port)
                    out[nid] = (host, known_port)
                    continue
            except Exception:
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
                _node_proxies.pop(nid, None)
        actor = _ProxyActor.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=nid)).remote(port)
        # Tracked BEFORE any blocking wait: even if the address fetch
        # below fails, shutdown() can still kill this actor.
        _node_proxies[nid] = (actor, n["host"], None)
        pending[nid] = (actor, n["host"])
    # Addresses collected after ALL spawns: N nodes cost one worker
    # startup of wall clock, not N.
    failed = []
    for nid, (actor, host) in pending.items():
        try:
            p = ray_tpu.get(actor.address.remote(), timeout=120)
        except Exception as e:
            # Don't leave a (actor, host, None) entry that a later call
            # would trust as healthy: kill and forget so the next
            # reconcile replaces the proxy.
            failed.append((nid, e))
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
            _node_proxies.pop(nid, None)
            continue
        _node_proxies[nid] = (actor, host, p)
        out[nid] = (host, p)
    if failed:
        raise RuntimeError(
            f"proxy address fetch failed on nodes {failed}; "
            f"{len(out)} proxies started")
    return out


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000) -> int:
    """HTTP ingress (parity: serve/_private/proxy.py uvicorn proxies;
    stdlib threading server this round). POST /<deployment> with a JSON
    body calls the deployment with that payload."""
    global _proxy_server
    _proxy_server = ThreadingHTTPServer((host, port), _ProxyHandler)
    t = threading.Thread(target=_proxy_server.serve_forever, daemon=True)
    t.start()
    return _proxy_server.server_address[1]


_rpc_ingress = None


def start_rpc_proxy(host: str = "127.0.0.1", port: int = 0) -> int:
    """Binary (msgpack-RPC) ingress beside HTTP — the second protocol
    (reference: the proxy's gRPC listener, serve/_private/proxy.py:13-38).
    See serve/rpc_ingress.py for the wire protocol; RpcIngressClient is
    the in-repo caller."""
    global _rpc_ingress
    from ray_tpu.serve.rpc_ingress import RpcIngress

    _rpc_ingress = RpcIngress()
    return _rpc_ingress.start(host, port)


def deploy_config(config):
    """Declarative multi-application deploy (reference: serve REST config /
    `serve deploy`); see serve/config_deploy.py for the schema."""
    from ray_tpu.serve.config_deploy import deploy_config as _impl

    return _impl(config)


def deploy_disagg(cfg, params, **kwargs):
    """Disaggregated LLM serving: prefill + decode replica pools under
    one router, device-plane KV handoff, prefix caching, per-pool
    autoscaling. See serve/llm_disagg.py."""
    from ray_tpu.serve.llm_disagg import deploy_disagg as _impl

    return _impl(cfg, params, **kwargs)


__all__ = [
    "deployment", "run", "get_deployment_handle", "status", "delete",
    "shutdown", "batch", "start_http_proxy", "start_rpc_proxy",
    "start_proxies", "deploy_config", "deploy_disagg", "Deployment",
    "DeploymentHandle", "DeploymentResponse", "DeploymentResponseGenerator",
    "AutoscalingConfig", "multiplexed", "get_multiplexed_model_id",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu('serve')
del _rlu
