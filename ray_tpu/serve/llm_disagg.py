"""Disaggregated LLM serving: prefill and decode as separate replica pools.

The single-process LLMEngine couples prefill compute to decode batching:
one replica runs both phases, so they fight for the same device and
scale on the same signal. This module splits them (reference: the
vLLM-style disaggregated prefill/decode deployments Serve LLM apps
wrap):

- **Prefill pool** (`PrefillServer`): bucketed whole-prompt prefill plus
  a cross-request prefix cache keyed on the prompt tokens — a full hit
  skips prefill compute entirely, a partial hit prefills only the
  suffix. Each replica returns the per-request KV as a device object
  (the router calls it with `tensor_transport="device"`), so the KV is
  pinned where it was produced and never travels through the router.
- **Decode pool** (`DecodeServer`): hosts a continuous-batching
  LLMEngine; `decode_stream` resolves the prefill KV over the cheapest
  device-plane route (same-mesh collective, counted host fallback) into
  a free slot via `submit_prefilled` — the happy path moves KV
  producer→consumer directly.
- **Router** (`DisaggHandle`): picks a prefill replica, passes the
  device ObjectRef (nested, unresolved) to a decode replica, and
  streams tokens back. A decode replica lost mid-stream resumes with
  ZERO dropped or duplicated tokens: a drained node evacuates the
  stream's KV + cursor through `device_objects.evacuate()` to the
  router, which replays undelivered tokens and re-submits the stream on
  a surviving replica; a hard crash falls back to a deterministic
  re-prefill of prompt + delivered tokens.
- **Per-pool autoscaling**: each pool carries an AutoscalingConfig with
  a replica-reported named metric — queue depth / TTFT for prefill,
  tokens-in-flight for decode — polled by the ServeController instead
  of the single handle-side queue-depth signal.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from collections import OrderedDict, deque

import numpy as np

from ray_tpu.models.generate import SamplingParams
from ray_tpu.models.llama import LlamaConfig, LlamaModel, init_kv_caches
from ray_tpu.serve.llm import LLMEngine, _Prefilled


def _note(event: str, n: int = 1) -> None:
    """Tick the serve-disagg gauges; never allowed to break the path."""
    try:
        from ray_tpu.util.metrics import note_serve_disagg

        note_serve_disagg(event, n)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Prefix cache
# ---------------------------------------------------------------------------


class PrefixCache:
    """Cross-request KV cache keyed on prompt tokens (LRU, bounded).

    Entries hold the host-side per-layer KV for one full prompt plus the
    last-position logits. Lookup semantics:

      full    — the exact prompt was seen before: reuse its KV AND its
                last-token logits (zero prefill compute; only sampling
                runs, with THIS request's params).
      partial — a cached prompt is a strict prefix of the new one:
                prefill only the suffix on top of the cached KV.
      miss    — run the whole bucketed prefill.
    """

    def __init__(self, max_entries: int = 32):
        self.max_entries = max(1, max_entries)
        self._entries: OrderedDict[tuple, dict] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def lookup(self, prompt) -> tuple[str, dict | None]:
        key = tuple(int(t) for t in prompt)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return "full", entry
            best_key, best = None, None
            for k, e in self._entries.items():
                n = len(k)
                if n < len(key) and key[:n] == k:
                    if best_key is None or n > len(best_key):
                        best_key, best = k, e
            if best is not None:
                self._entries.move_to_end(best_key)
                self.hits += 1
                return "partial", best
            self.misses += 1
            return "miss", None

    def insert(self, prompt, kv_host: list, last_logits) -> None:
        key = tuple(int(t) for t in prompt)
        with self._lock:
            self._entries[key] = {
                "prefix_len": len(key),
                "kv": kv_host,  # [(k, v)] per layer, numpy (Hkv, plen, D)
                "logits": np.asarray(last_logits),
            }
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def stats(self) -> dict:
        with self._lock:
            n = len(self._entries)
        total = self.hits + self.misses
        return {"entries": n, "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": self.hits / total if total else 0.0}


# ---------------------------------------------------------------------------
# Prefill pool
# ---------------------------------------------------------------------------


class PrefillEngine:
    """Compiled prefill programs for the prefill pool: bucketed
    whole-prompt prefill plus a suffix variant that continues on top of
    a cached KV prefix (the prefix-cache partial-hit path)."""

    def __init__(self, cfg: LlamaConfig, params, *, max_len: int = 1024,
                 rng_seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.model = LlamaModel(cfg)
        self._jax, self._jnp = jax, jnp
        self._rng = jax.random.PRNGKey(rng_seed)
        model, cfg_, max_len_ = self.model, cfg, max_len

        @jax.jit
        def prefill_one(params, tokens):
            positions = jnp.arange(tokens.shape[1])[None, :]
            caches1 = init_kv_caches(cfg_, 1, max_len_)
            logits, new = model.apply(params, tokens, positions,
                                      kv_caches=caches1)
            return logits[0], [(k[0], v[0]) for k, v, _l in new]

        @jax.jit
        def prefill_suffix(params, tokens, start, kv_prefix):
            # tokens: (1, sbucket) right-padded suffix at absolute
            # positions start.. ; kv_prefix per layer (Hkv, max_len, D)
            # valid on [0, start). The write window [start, start+sb)
            # must fit max_len (callers guard) or dynamic_update_slice
            # clamping would relocate it over the prefix.
            positions = start + jnp.arange(tokens.shape[1])[None, :]
            caches1 = [(k[None], v[None], start) for k, v in kv_prefix]
            logits, new = model.apply(params, tokens, positions,
                                      kv_caches=caches1)
            return logits[0], [(k[0], v[0]) for k, v, _l in new]

        self._prefill_one = prefill_one
        self._prefill_suffix = prefill_suffix

    def _bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _sample_first(self, last_logits, sp: SamplingParams) -> int:
        from ray_tpu.models.generate import sample_logits

        self._rng, srng = self._jax.random.split(self._rng)
        tok = sample_logits(self._jnp.asarray(last_logits)[None], srng, sp)
        return int(np.asarray(tok)[0])

    def prefill(self, prompt: np.ndarray, sp: SamplingParams,
                cache: PrefixCache | None = None) -> dict:
        """Run (or skip, on a cache hit) prefill for one prompt. Returns
        {"kv": [(k, v)] jax arrays trimmed to prompt_len, "first_token",
        "prompt_len", "kv_len", "prefix_hit"}."""
        jnp = self._jnp
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        hit, entry = cache.lookup(prompt) if cache is not None \
            else ("miss", None)
        if hit == "partial":
            start = entry["prefix_len"]
            sbucket = self._bucket(plen - start)
            if start + sbucket > self.max_len:
                # Suffix write window would clamp past max_len: run the
                # whole-prompt path instead (correctness over reuse).
                hit, entry = "miss", None
        if hit == "full":
            kv = [(jnp.asarray(k), jnp.asarray(v)) for k, v in entry["kv"]]
            first = self._sample_first(entry["logits"], sp)
            _note("prefix_full_hits")
            return {"kv": kv, "first_token": first, "prompt_len": plen,
                    "kv_len": plen, "prefix_hit": "full"}
        if hit == "partial":
            start = entry["prefix_len"]
            sbucket = self._bucket(plen - start)
            suffix = np.zeros((1, sbucket), np.int32)
            suffix[0, : plen - start] = prompt[start:]
            kv_prefix = []
            for k, v in entry["kv"]:
                Hkv, _pl, D = k.shape
                kp = np.zeros((Hkv, self.max_len, D), k.dtype)
                vp = np.zeros((Hkv, self.max_len, D), v.dtype)
                kp[:, :start] = k[:, :start]
                vp[:, :start] = v[:, :start]
                kv_prefix.append((jnp.asarray(kp, self.cfg.dtype),
                                  jnp.asarray(vp, self.cfg.dtype)))
            logits, kv_full = self._prefill_suffix(
                self.params, jnp.asarray(suffix), jnp.int32(start),
                kv_prefix)
            last_logits = logits[plen - start - 1]
            _note("prefix_partial_hits")
        else:
            bucket = self._bucket(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = prompt
            logits, kv_full = self._prefill_one(self.params,
                                                jnp.asarray(padded))
            last_logits = logits[plen - 1]
        kv = [(k[:, :plen], v[:, :plen]) for k, v in kv_full]
        if cache is not None:
            cache.insert(prompt,
                         [(np.asarray(k), np.asarray(v)) for k, v in kv],
                         np.asarray(last_logits))
        first = self._sample_first(last_logits, sp)
        return {"kv": kv, "first_token": first, "prompt_len": plen,
                "kv_len": plen, "prefix_hit": hit}


class PrefillServer:
    """Prefill-pool deployment callable.

    Requests funnel through an internal queue serviced by ONE worker
    thread (the compiled programs are single-device; serialization also
    makes queue_depth an honest autoscaling signal even though the
    replica actor runs with max_concurrency lanes). The router calls
    `prefill` with tensor_transport="device", so the returned KV arrays
    pin HERE and ship over the device plane straight to decode."""

    def __init__(self, cfg: LlamaConfig, params, *, max_len: int = 1024,
                 prefix_cache_size: int = 32, rng_seed: int = 0):
        self.engine = PrefillEngine(cfg, params, max_len=max_len,
                                    rng_seed=rng_seed)
        self.cache = PrefixCache(prefix_cache_size)
        self._q: queue.Queue = queue.Queue()
        self._ttft = deque(maxlen=256)
        self._served = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="prefill-engine")
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            payload, done, holder, t0 = item
            try:
                sp = _sampling_from(payload)
                holder["result"] = self.engine.prefill(
                    payload["prompt_tokens"], sp, self.cache)
            except BaseException as e:  # noqa: BLE001
                holder["error"] = e
            self._ttft.append(time.monotonic() - t0)
            self._served += 1
            done.set()

    def prefill(self, payload: dict) -> dict:
        done = threading.Event()
        holder: dict = {}
        self._q.put((payload, done, holder, time.monotonic()))
        if not done.wait(timeout=300):
            raise TimeoutError("prefill queue wait exceeded 300s")
        if "error" in holder:
            raise holder["error"]
        return holder["result"]

    def report_metrics(self) -> dict:
        ttft = sorted(self._ttft)
        pick = lambda q: ttft[min(len(ttft) - 1,  # noqa: E731
                                  int(q * len(ttft)))] if ttft else 0.0
        out = {
            "queue_depth": float(self._q.qsize()),
            "served": float(self._served),
            "ttft_p50_ms": pick(0.5) * 1e3,
            "ttft_p99_ms": pick(0.99) * 1e3,
        }
        for k, v in self.cache.stats().items():
            out[f"prefix_cache_{k}"] = float(v)
        return out

    def prepare_drain(self):
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and self._q.qsize():
            time.sleep(0.05)


def _sampling_from(payload: dict) -> SamplingParams:
    return SamplingParams(
        max_new_tokens=int(payload.get("max_new_tokens", 64)),
        temperature=float(payload.get("temperature", 0.0)),
        top_k=int(payload.get("top_k", 0)),
        top_p=float(payload.get("top_p", 1.0)),
        eos_token=payload.get("eos_token"))


# ---------------------------------------------------------------------------
# Decode pool
# ---------------------------------------------------------------------------


class DecodeServer:
    """Decode-pool deployment callable hosting one continuous-batching
    LLMEngine. `decode_stream` resolves the prefill pool's device-object
    KV in THIS process (cheapest route) and admits it via
    submit_prefilled — the KV never round-trips through the router.

    Zero-loss drain: a DrainNotice (node preemption) quiesces the
    engine, snapshots every in-flight stream (KV + cursor + token
    history), and pins the snapshots with the ROUTER as ref owner, so
    the raylet's drain pipeline evacuates them through
    device_objects.evacuate() to the router process for resume."""

    def __init__(self, cfg: LlamaConfig, params, *, max_batch: int = 4,
                 max_len: int = 1024, decode_chunk: int = 8,
                 page_size: int = 0, kv_pool_tokens: int = 0,
                 stream_buffer: int = 256):
        self.cfg = cfg
        self.engine = LLMEngine(cfg, params, max_batch=max_batch,
                                max_len=max_len, decode_chunk=decode_chunk,
                                page_size=page_size,
                                kv_pool_tokens=kv_pool_tokens,
                                stream_buffer=stream_buffer)
        self._router_wires: dict[str, object] = {}
        self._evac_streams = 0
        self._decode_requests = 0
        try:
            from ray_tpu._private import device_objects

            # Runs INSIDE device_objects.evacuate() before it gathers
            # pins — a DrainNotice listener would lose the race against
            # the raylet's evacuation step, which fires milliseconds
            # after the notice.
            device_objects.add_evacuation_preparer(self._evacuate_streams)
        except Exception:
            pass  # no runtime (unit tests drive the engine directly)

    def _evacuate_streams(self):
        try:
            if not self.engine.quiesce_for_drain(timeout=8.0):
                return
            snaps = self.engine.snapshot_active_streams()
            if not snaps:
                return
            from ray_tpu._private import device_objects
            from ray_tpu._private.api_internal import get_core_worker

            cw = get_core_worker()
            reg = device_objects.registry()
            for tag, snap in snaps.items():
                wire = self._router_wires.get(tag)
                if wire is None:
                    continue
                prefix = f"disagg:{tag}"
                i = 0
                for k, v in snap["kv"]:
                    reg.pin(f"{prefix}#{i}", k, cw)
                    reg.pin(f"{prefix}#{i + 1}", v, cw)
                    i += 2
                state = np.asarray([snap["lens"], snap["token"],
                                    snap["generated"], snap["prompt_len"]],
                                   np.float64)
                reg.pin(f"{prefix}#{i}", state, cw)
                # History LAST: the router polls this key as the
                # all-leaves-landed sentinel after repin.
                hist = np.asarray(snap["history"], np.int64)
                reg.pin(f"{prefix}#{i + 1}", hist, cw)
                reg.note_ref_owner(prefix, wire)
                self._evac_streams += 1
                _note("streams_evacuated")
        except Exception:
            pass  # the router's re-prefill fallback still covers us

    def decode_stream(self, meta: dict, kv_ref):
        import ray_tpu

        kv_obj = ray_tpu.get(kv_ref)  # device stubs resolve HERE
        sp = _sampling_from(meta)
        resume = meta.get("resume")
        if resume:
            pack = _Prefilled(kv_obj["kv"], resume["token"],
                              kv_obj["prompt_len"], resume["lens"],
                              resume["generated"], resume["history"],
                              emit_first=False)
        else:
            pack = _Prefilled(kv_obj["kv"], kv_obj["first_token"],
                              kv_obj["prompt_len"], 0, 0, [],
                              emit_first=True)
            pack.lens = int(kv_obj["kv_len"])
        tag = meta.get("rsid", "")
        if meta.get("router_wire") is not None:
            self._router_wires[tag] = meta["router_wire"]
        handle = self.engine.submit_prefilled(pack, sp, tag=tag)
        self._decode_requests += 1
        try:
            for tok in handle:
                yield tok
        finally:
            self._router_wires.pop(tag, None)

    def report_metrics(self) -> dict:
        from ray_tpu._private import device_objects

        out = self.engine.report_metrics()
        out["decode_requests"] = float(self._decode_requests)
        out["streams_evacuated"] = float(self._evac_streams)
        out["plane_counters"] = device_objects.counters()
        try:
            import ray_tpu

            out["node_id"] = ray_tpu.get_runtime_context().node_id
        except Exception:
            pass
        return out

    def prepare_drain(self):
        """Controller scale-in: wait for in-flight streams to finish
        (they keep draining over the replica's other concurrency lanes
        while this call blocks)."""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if self.engine.num_active() == 0 and \
                    self.engine.queue_depth() == 0:
                return
            time.sleep(0.1)


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------


class DisaggHandle:
    """Routes one request across the two pools: prefill (device-return
    KV) → decode (streamed tokens), with zero-loss resume when a decode
    replica dies mid-stream."""

    def __init__(self, prefill_handle, decode_handle, *, n_layers: int,
                 prefill_name: str = "", decode_name: str = "",
                 evac_wait_s: float = 6.0, max_resumes: int = 3):
        self._prefill = prefill_handle
        self._decode = decode_handle
        self._n_layers = n_layers
        self.prefill_name = prefill_name
        self.decode_name = decode_name
        self._evac_wait_s = evac_wait_s
        self._max_resumes = max_resumes
        self.stats = {"requests": 0, "completed": 0, "resumes": 0,
                      "replayed_tokens": 0, "evac_resumes": 0,
                      "fallback_reprefills": 0}
        try:
            from ray_tpu._private.api_internal import get_core_worker

            self._wire = get_core_worker().address.to_wire()
        except Exception:
            self._wire = None

    # -- pool plumbing --

    def _prefill_ref(self, payload: dict):
        """Run prefill on the least-loaded prefill replica with a
        device-object return: the KV pins on the prefill worker with
        THIS process as ref owner; only the descriptor travels."""
        idx, replica = self._prefill._pick_replica()
        try:
            return replica.handle_request.options(
                tensor_transport="device").remote(
                    "prefill", [payload], {}, "")
        finally:
            # The prefill pool scales on its replica-reported queue
            # depth, not handle-side outstanding counts.
            self._prefill._done(idx)

    def _decode_gen(self, meta: dict, kv_ref, attempts: int = 1):
        """Submit one decode stream. attempts > 1 rides out the window
        after a replica death where _pick_replica can still hand back
        the dead replica (the controller needs a health tick or two to
        recreate it and push the new set)."""
        last = None
        for _ in range(max(1, attempts)):
            try:
                return self._decode.options(
                    stream=True,
                    method_name="decode_stream").remote(meta, kv_ref)
            except Exception as e:  # dead replica / empty set mid-recreate
                last = e
                time.sleep(0.5)
        raise last

    def _read_evacuated(self, rsid: str) -> dict | None:
        """Poll this process's registry for a drain-evacuated stream
        snapshot (device_objects.handle_repin lands the pins here under
        their original keys). Returns None when no evacuation arrived
        within the window — the caller falls back to re-prefill."""
        from ray_tpu._private import device_objects

        reg = device_objects.registry()
        prefix = f"disagg:{rsid}"
        last_key = f"{prefix}#{2 * self._n_layers + 1}"
        deadline = time.monotonic() + self._evac_wait_s
        while reg.get(last_key) is None:
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.2)
        kv = []
        for li in range(self._n_layers):
            k = reg.get(f"{prefix}#{2 * li}")
            v = reg.get(f"{prefix}#{2 * li + 1}")
            if k is None or v is None:
                return None
            kv.append((np.asarray(k), np.asarray(v)))
        state = np.asarray(reg.get(f"{prefix}#{2 * self._n_layers}"))
        hist = [int(t) for t in np.asarray(reg.get(last_key))]
        reg.release_prefix(prefix, counted=False)
        return {"kv": kv, "lens": int(state[0]), "token": int(state[1]),
                "generated": int(state[2]), "prompt_len": int(state[3]),
                "history": hist}

    def _reship_kv(self, snap: dict):
        """Pin the evacuated KV in THIS process and hand the new decode
        replica a device ref to it — the resume handoff rides the same
        plane as the original one."""
        import jax.numpy as jnp

        from ray_tpu._private import device_objects

        kv = [(jnp.asarray(k), jnp.asarray(v)) for k, v in snap["kv"]]
        return device_objects.device_put({
            "kv": kv, "prompt_len": snap["prompt_len"],
            "kv_len": snap["lens"], "first_token": snap["token"]})

    # -- request path --

    def stream(self, payload: dict):
        """Generator of tokens for one request across both pools."""
        rsid = uuid.uuid4().hex
        self.stats["requests"] += 1
        _note("streams_started")
        meta = {"rsid": rsid, "router_wire": self._wire,
                **{k: payload[k] for k in ("max_new_tokens", "temperature",
                                           "top_k", "top_p", "eos_token")
                   if k in payload}}
        max_new = int(payload.get("max_new_tokens", 64))
        eos = payload.get("eos_token")
        gen = self._decode_gen(meta, self._prefill_ref(payload))
        delivered: list[int] = []
        # A fallback re-prefill starts a fresh engine lineage whose
        # history/generated counters are LOCAL to it: `base` maps that
        # lineage's token 0 onto the global stream position.
        base = 0
        resumes = 0
        while True:
            try:
                for tok in gen:
                    delivered.append(tok)
                    yield tok
                self.stats["completed"] += 1
                _note("streams_completed")
                return
            except Exception:
                if resumes >= self._max_resumes:
                    raise
                resumes += 1
                self.stats["resumes"] += 1
                _note("stream_resumes")
                try:
                    gen.cancel()
                except Exception:
                    pass
                if len(delivered) >= max_new or \
                        (eos is not None and delivered
                         and delivered[-1] == eos):
                    # The replica died between the final token and the
                    # done signal — nothing left to resume.
                    self.stats["completed"] += 1
                    _note("streams_completed")
                    return
                snap = self._read_evacuated(rsid)
                if snap is not None:
                    self.stats["evac_resumes"] += 1
                    # Replay tokens the consumer never saw (the engine's
                    # history includes ones that were still queued or in
                    # a lost next_chunks reply).
                    for tok in snap["history"][len(delivered) - base:]:
                        delivered.append(tok)
                        self.stats["replayed_tokens"] += 1
                        yield tok
                    if len(delivered) >= max_new or \
                            (eos is not None and delivered
                             and delivered[-1] == eos):
                        self.stats["completed"] += 1
                        _note("streams_completed")
                        return
                    meta = dict(meta, resume={
                        "token": snap["token"], "lens": snap["lens"],
                        "generated": snap["generated"],
                        "history": snap["history"]})
                    gen = self._decode_gen(meta, self._reship_kv(snap),
                                           attempts=24)
                else:
                    # No evacuation landed (hard crash): deterministic
                    # re-prefill of prompt + delivered tokens. BOTH the
                    # prefill payload and the decode meta get the shrunk
                    # budget — the new engine stream starts at
                    # generated=0, so its max_new must exclude what was
                    # already streamed or it decodes past the request's
                    # budget.
                    self.stats["fallback_reprefills"] += 1
                    _note("fallback_reprefills")
                    base = len(delivered)
                    payload2 = dict(payload)
                    payload2["prompt_tokens"] = list(
                        np.asarray(payload["prompt_tokens"],
                                   np.int64).reshape(-1)) + delivered
                    payload2["max_new_tokens"] = max_new - base
                    meta = dict(meta, max_new_tokens=max_new - base)
                    meta.pop("resume", None)
                    gen = self._decode_gen(meta, self._prefill_ref(payload2),
                                           attempts=24)

    def generate(self, payload: dict) -> list[int]:
        return list(self.stream(payload))

    def pool_metrics(self) -> dict:
        """Replica-reported metrics for both pools (one poll fan-out)."""
        import ray_tpu

        out: dict = {}
        for label, handle in (("prefill", self._prefill),
                              ("decode", self._decode)):
            rows = []
            for r in handle._get_replicas():
                try:
                    rows.append(ray_tpu.get(r.report_metrics.remote(),
                                            timeout=10))
                except Exception:
                    pass
            out[label] = rows
        return out


# ---------------------------------------------------------------------------
# Deployment helper
# ---------------------------------------------------------------------------


def deploy_disagg(cfg: LlamaConfig, params, *, name: str = "llm",
                  prefill_replicas: int = 2, decode_replicas: int = 2,
                  max_batch: int = 4, max_len: int = 512,
                  decode_chunk: int = 4, page_size: int = 0,
                  kv_pool_tokens: int = 0, prefix_cache_size: int = 32,
                  stream_buffer: int = 256,
                  prefill_autoscaling: dict | None = None,
                  decode_autoscaling: dict | None = None,
                  prefill_actor_options: dict | None = None,
                  decode_actor_options: dict | None = None) -> DisaggHandle:
    """Deploy the two pools under one router and return a DisaggHandle.

    Pool autoscaling configs default to the per-pool named metrics:
    prefill scales on queue_depth, decode on tokens_in_flight. Replicas
    run with max_concurrency > 1 — required so prepare_drain (blocking
    until streams finish) cannot deadlock the next_chunks pulls those
    streams need."""
    from ray_tpu import serve

    prefill_asc = prefill_autoscaling
    if prefill_asc is None:
        prefill_asc = {"min_replicas": prefill_replicas,
                       "max_replicas": prefill_replicas}
    prefill_asc.setdefault("metric", "queue_depth")
    prefill_asc.setdefault("target_value", 4.0)
    decode_asc = decode_autoscaling
    if decode_asc is None:
        decode_asc = {"min_replicas": decode_replicas,
                      "max_replicas": decode_replicas}
    decode_asc.setdefault("metric", "tokens_in_flight")
    decode_asc.setdefault("target_value", float(max_batch * 64))

    prefill_dep = serve.deployment(
        PrefillServer, name=f"{name}-prefill",
        num_replicas=prefill_replicas,
        ray_actor_options={"max_concurrency": 8,
                           **(prefill_actor_options or {})},
        autoscaling_config=prefill_asc,
    ).bind(cfg, params, max_len=max_len,
           prefix_cache_size=prefix_cache_size)
    decode_dep = serve.deployment(
        DecodeServer, name=f"{name}-decode",
        num_replicas=decode_replicas,
        ray_actor_options={"max_concurrency": 16,
                           **(decode_actor_options or {})},
        autoscaling_config=decode_asc,
    ).bind(cfg, params, max_batch=max_batch, max_len=max_len,
           decode_chunk=decode_chunk, page_size=page_size,
           kv_pool_tokens=kv_pool_tokens, stream_buffer=stream_buffer)
    prefill_handle = serve.run(prefill_dep)
    decode_handle = serve.run(decode_dep)
    return DisaggHandle(prefill_handle, decode_handle,
                        n_layers=cfg.n_layers,
                        prefill_name=f"{name}-prefill",
                        decode_name=f"{name}-decode")
