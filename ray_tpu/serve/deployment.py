"""Deployments, replicas, handles, and routing.

Parity: reference python/ray/serve — @serve.deployment (api.py:258),
replica actors (_private/replica.py), handle-side Router with
PowerOfTwoChoicesReplicaScheduler (router.py:290), @serve.batch dynamic
batching (batching.py). Differences this round: request routing and
dynamic batching live entirely handle-side (the newer reference also moved
queue-length metrics into the handle), and replicas execute requests
through the ordered actor queue.
"""

from __future__ import annotations

import queue as _queue
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu


@dataclass
class AutoscalingConfig:
    """Parity: serve/_private/autoscaling_policy.py BasicAutoscalingPolicy."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 5.0
    # Replica-reported named metric ("queue_depth", "tokens_in_flight",
    # ...): when set, the controller polls each replica's report_metrics()
    # and scales this pool on sum(metric)/target_value instead of the
    # handle-side outstanding-request count. This is what lets a
    # disaggregated prefill pool scale on queue depth while the decode
    # pool scales on tokens-in-flight (reference: Serve autoscaling on
    # custom metrics).
    metric: str | None = None
    target_value: float | None = None
    look_back_period_s: float = 10.0


@dataclass
class DeploymentConfig:
    name: str
    num_replicas: int = 1
    ray_actor_options: dict = field(default_factory=dict)
    autoscaling_config: AutoscalingConfig | None = None
    max_ongoing_requests: int = 100
    user_config: Any = None


# --- model multiplexing (parity: serve/multiplex.py) -----------------------

# Plain module global, not TLS: replica actors execute requests serially
# (ordered actor queue), and a threading.local here would make the replica
# class blob unpicklable (cloudpickle captures referenced globals by value).
_current_model_id: str = ""


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id the current request was routed with
    (reference: serve.get_multiplexed_model_id)."""
    import ray_tpu.serve.deployment as _dep

    return _dep._current_model_id


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator for a per-model loader method: results are LRU-cached on
    the replica, at most `max_num_models_per_replica` resident (reference:
    serve/multiplex.py _ModelMultiplexWrapper)."""

    def wrap(fn):
        def loader(self, model_id: str):
            cache = getattr(self, "_serve_model_cache", None)
            if cache is None:
                from collections import OrderedDict

                cache = self._serve_model_cache = OrderedDict()
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            model = fn(self, model_id)
            cache[model_id] = model
            while len(cache) > max_num_models_per_replica:
                cache.popitem(last=False)
            return model

        loader._serve_multiplexed = True
        return loader

    if _fn is not None:
        return wrap(_fn)
    return wrap


@ray_tpu.remote
class ReplicaActor:
    """Hosts one copy of the deployment callable."""

    def __init__(self, callable_blob: bytes, init_args, init_kwargs,
                 user_config=None):
        from ray_tpu._private import serialization

        target = serialization.loads_func(callable_blob)
        if isinstance(target, type):
            self._instance = target(*init_args, **(init_kwargs or {}))
        else:
            self._instance = target
        if user_config is not None and hasattr(self._instance,
                                               "reconfigure"):
            self._instance.reconfigure(user_config)

    def handle_request(self, method: str, args, kwargs, model_id: str = ""):
        import ray_tpu.serve.deployment as _dep

        fn = self._instance if method == "__call__" \
            else getattr(self._instance, method)
        _dep._current_model_id = model_id
        try:
            return fn(*args, **(kwargs or {}))
        finally:
            _dep._current_model_id = ""

    def loaded_model_ids(self) -> list[str]:
        cache = getattr(self._instance, "_serve_model_cache", None)
        return list(cache.keys()) if cache else []

    def handle_batch(self, method: str, batched_args: list):
        fn = self._instance if method == "__call__" \
            else getattr(self._instance, method)
        return fn([args[0] if args else None for args, _kwargs in batched_args])

    def reconfigure(self, user_config):
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)
        return True

    def health_check(self):
        return True

    def report_metrics(self) -> dict:
        """Named metrics for per-pool autoscaling: forwarded from the
        deployment callable when it implements report_metrics()."""
        fn = getattr(self._instance, "report_metrics", None)
        if fn is None:
            return {}
        try:
            return dict(fn())
        except Exception:
            return {}

    def prepare_drain(self) -> bool:
        """Called by the controller before killing this replica on
        scale-in: blocks until the callable has finished (or evacuated)
        its in-flight work. Replicas hosting streaming engines need
        max_concurrency > 1 so concurrent next_chunks pulls can keep
        draining streams while this call waits."""
        fn = getattr(self._instance, "prepare_drain", None)
        if fn is None:
            return True
        try:
            fn()
        except Exception:
            pass
        return True

    # -- streaming (reference: serve streaming responses / generator
    # deployments, serve/handle.py DeploymentResponseGenerator). The
    # generator lives on the replica; the client pulls chunks with
    # follow-up actor calls, so memory stays bounded on both sides. --

    def start_stream(self, method: str, args, kwargs, model_id: str = ""):
        import uuid

        fn = self._instance if method == "__call__" \
            else getattr(self._instance, method)
        # Calling a generator function only CREATES the generator — the
        # body runs inside next(), so the model id must be active around
        # every next_chunks pull, not just here. Stored per-stream.
        gen = fn(*args, **(kwargs or {}))
        if not hasattr(gen, "__next__"):
            gen = iter(gen)
        sid = uuid.uuid4().hex
        if not hasattr(self, "_streams"):
            self._streams = {}
        self._streams[sid] = (gen, model_id)
        return sid

    def next_chunks(self, stream_id: str, max_chunks: int = 8):
        import ray_tpu.serve.deployment as _dep

        entry = self._streams.get(stream_id)
        if entry is None:
            raise KeyError(f"unknown stream {stream_id}")
        gen, model_id = entry
        values, done = [], False
        _dep._current_model_id = model_id
        try:
            for _ in range(max_chunks):
                try:
                    values.append(next(gen))
                except StopIteration:
                    done = True
                    del self._streams[stream_id]
                    break
        finally:
            _dep._current_model_id = ""
        return {"values": values, "done": done}

    def cancel_stream(self, stream_id: str):
        entry = self._streams.pop(stream_id, None)
        if entry is not None and hasattr(entry[0], "close"):
            entry[0].close()
        return True


class Deployment:
    """The declarative object produced by @serve.deployment."""

    def __init__(self, target, config: DeploymentConfig,
                 init_args=(), init_kwargs=None):
        self._target = target
        self._config = config
        self._init_args = init_args
        self._init_kwargs = init_kwargs or {}

    @property
    def name(self) -> str:
        return self._config.name

    def options(self, **kwargs) -> "Deployment":
        cfg = DeploymentConfig(**{**self._config.__dict__, **{
            k: v for k, v in kwargs.items()
            if k in DeploymentConfig.__dataclass_fields__}})
        return Deployment(self._target, cfg, self._init_args, self._init_kwargs)

    def bind(self, *args, **kwargs) -> "Deployment":
        return Deployment(self._target, self._config, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name}, replicas={self._config.num_replicas})"


def deployment(target=None, *, name: str | None = None, num_replicas: int = 1,
               ray_actor_options: dict | None = None,
               autoscaling_config: dict | AutoscalingConfig | None = None,
               max_ongoing_requests: int = 100, user_config=None):
    """@serve.deployment decorator (parity: serve/api.py:258)."""

    def wrap(t):
        asc = autoscaling_config
        if isinstance(asc, dict):
            asc = AutoscalingConfig(**asc)
        cfg = DeploymentConfig(
            name=name or getattr(t, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options or {},
            autoscaling_config=asc,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config)
        return Deployment(t, cfg)

    if target is not None:
        return wrap(target)
    return wrap


class _BatchQueue:
    """Handle-side dynamic batching (parity: serve/batching.py)."""

    def __init__(self, submit_batch: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.submit_batch = submit_batch
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.pending: list = []
        self.lock = threading.Lock()
        self.timer: threading.Timer | None = None

    def add(self, item, result_slot):
        with self.lock:
            self.pending.append((item, result_slot))
            if len(self.pending) >= self.max_batch_size:
                batch, self.pending = self.pending, []
                if self.timer:
                    self.timer.cancel()
                    self.timer = None
            else:
                batch = None
                if self.timer is None:
                    self.timer = threading.Timer(self.timeout, self._flush)
                    self.timer.daemon = True
                    self.timer.start()
        if batch:
            self.submit_batch(batch)

    def _flush(self):
        with self.lock:
            batch, self.pending = self.pending, []
            self.timer = None
        if batch:
            self.submit_batch(batch)


class DeploymentResponse:
    """Future-like response from handle.remote()."""

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None
        self._ref = None
        self._retry: Callable | None = None  # death-retry hook (handle sets)

    def _resolve_ref(self, ref):
        self._ref = ref
        self._event.set()

    def _resolve_value(self, value):
        self._value = value
        self._event.set()

    def _resolve_error(self, err: BaseException):
        self._error = err
        self._event.set()

    def __await__(self):
        """Async callers `await handle.remote(...)` directly (reference:
        DeploymentResponse is awaitable in async contexts, with no
        implicit deadline). The wait is poll-based — no executor thread is
        parked per pending response, so wide async fan-outs aren't capped
        by the thread pool."""
        import asyncio

        async def waiter():
            while True:
                if self._event.is_set():
                    if self._ref is None:
                        return self.result(timeout=None)
                    ready, _ = ray_tpu.wait([self._ref], timeout=0)
                    if ready:
                        return self.result(timeout=None)
                await asyncio.sleep(0.005)

        return waiter().__await__()

    def result(self, timeout: float | None = 60.0):
        start = time.monotonic()

        def remaining():
            if timeout is None:
                return None
            return max(0.1, timeout - (time.monotonic() - start))

        if not self._event.wait(timeout):
            raise TimeoutError("deployment response timed out")
        if self._error is not None:
            raise self._error
        if self._ref is not None:
            try:
                return ray_tpu.get(self._ref, timeout=remaining())
            except ray_tpu.exceptions.ActorError:
                # Replica died mid-request: retry on another replica within
                # the caller's ORIGINAL timeout budget (reference: handles
                # retry system-level replica failures).
                if self._retry is not None:
                    retry, self._retry = self._retry, None
                    return retry(remaining())
                raise
        return self._value


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment response (reference:
    serve/handle.py DeploymentResponseGenerator for generator handlers)."""

    def __init__(self, handle: "DeploymentHandle", idx: int, replica,
                 stream_id: str):
        self._handle = handle
        self._idx = idx
        self._replica = replica
        self._sid = stream_id
        self._buffer: list = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        while not self._buffer:
            if self._done:
                raise StopIteration
            chunk = ray_tpu.get(
                self._replica.next_chunks.remote(self._sid), timeout=60)
            self._buffer.extend(chunk["values"])
            if chunk["done"]:
                self._done = True
                self._handle._done(self._idx)
                if not self._buffer:
                    raise StopIteration
        return self._buffer.pop(0)

    def cancel(self):
        if not self._done:
            self._done = True
            self._handle._done(self._idx)
            try:
                self._replica.cancel_stream.remote(self._sid)
            except Exception:
                pass


class _RouterState:
    """Shared routing state for a deployment: replica cache, per-replica
    outstanding counts, and multiplexed-model residency.  One instance is
    shared by a handle and every handle derived from it via .options()
    (reference: handle clones share one Router, router.py)."""

    def __init__(self):
        self.replicas: list = []
        self.outstanding: dict[int, int] = {}
        self.model_replicas: dict[str, set[int]] = {}
        self.lock = threading.Lock()
        self.last_refresh = 0.0
        # Long-poll push state (reference: long_poll.py LongPollClient):
        # a daemon thread parks on the controller and applies replica-set
        # updates the moment they are published.
        self.poll_version = 0
        self.poll_thread: threading.Thread | None = None
        self.poll_stop = threading.Event()

    def start_long_poll(self, name: str, controller) -> None:
        key = f"replicas:{name}"

        def loop():
            while not self.poll_stop.is_set():
                t0 = time.monotonic()
                try:
                    upd = ray_tpu.get(
                        controller.long_poll.remote(
                            {key: self.poll_version}, 10.0),
                        timeout=30)
                except Exception:
                    if self.poll_stop.wait(1.0):
                        return
                    continue
                if key not in upd and time.monotonic() - t0 < 1.0:
                    # Instant empty reply = the controller's parked-poll
                    # slots are exhausted (it answers {} immediately, not
                    # after the 10s park). Re-calling in a tight loop
                    # would hammer its concurrency lanes; back off.
                    if self.poll_stop.wait(0.5):
                        return
                if key in upd:
                    ver, reps = upd[key]
                    with self.lock:
                        self.poll_version = ver
                        if len(reps) != len(self.replicas):
                            self.model_replicas.clear()
                        self.replicas = reps
                        self.last_refresh = time.monotonic()
                        for i in range(len(reps)):
                            self.outstanding.setdefault(i, 0)

        with self.lock:  # check-and-start must be atomic across threads
            if self.poll_thread is not None and self.poll_thread.is_alive():
                return
            self.poll_thread = threading.Thread(
                target=loop, daemon=True, name="serve-longpoll")
            self.poll_thread.start()


class DeploymentHandle:
    """Routes requests to replicas: power-of-two-choices on outstanding
    per-replica request counts (reference: router.py:290)."""

    def __init__(self, deployment_name: str, controller, method: str = "__call__",
                 batching: tuple[int, float] | None = None,
                 multiplexed_model_id: str = "",
                 router: _RouterState | None = None, stream: bool = False):
        self.deployment_name = deployment_name
        self._controller = controller
        self._method = method
        self._model_id = multiplexed_model_id
        self._stream = stream
        self._router = router or _RouterState()
        self._batchq: _BatchQueue | None = None
        if batching:
            self._batchq = _BatchQueue(self._submit_batch, batching[0],
                                       batching[1])
        # (idx, ref) pairs not yet observed complete; a reaper thread
        # retires them so "ongoing requests" means submitted-but-unfinished
        # (the autoscaling metric), not merely mid-submit.
        self._inflight: list = []
        self._reaper: threading.Thread | None = None

    def __reduce__(self):
        # Handles travel into replicas (deployment-graph composition) and
        # rebuild with fresh router state there — the lock/queues are
        # process-local; the batching CONFIG survives the trip.
        batching = (self._batchq.max_batch_size, self._batchq.timeout) \
            if self._batchq is not None else None
        return (_rebuild_handle, (self.deployment_name, self._controller,
                                  self._method, self._model_id, batching))

    def options(self, method_name: str | None = None,
                batching: tuple[int, float] | None = None,
                multiplexed_model_id: str | None = None,
                stream: bool | None = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self._controller,
            method_name or self._method, batching,
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id,
            router=self._router,
            stream=self._stream if stream is None else stream)

    # Routing state lives on the shared router; these aliases keep the
    # method bodies below reading naturally.

    @property
    def _lock(self):
        return self._router.lock

    @property
    def _replicas(self):
        return self._router.replicas

    @_replicas.setter
    def _replicas(self, value):
        self._router.replicas = value

    @property
    def _outstanding(self):
        return self._router.outstanding

    @property
    def _model_replicas(self):
        return self._router.model_replicas

    @property
    def _last_refresh(self):
        return self._router.last_refresh

    @_last_refresh.setter
    def _last_refresh(self, value):
        self._router.last_refresh = value

    # -- replica set maintenance: long-poll push with a slow TTL-refresh
    # fallback (reference: router updates via LongPollClient) --

    def _get_replicas(self):
        self._router.start_long_poll(self.deployment_name, self._controller)
        now = time.monotonic()
        # The push thread keeps last_refresh current; the pull below only
        # fires when the push path is unavailable (controller restart) or
        # before the first push lands.
        if now - self._last_refresh > 5.0 or not self._replicas:
            reps = ray_tpu.get(self._controller.get_replicas.remote(
                self.deployment_name))
            with self._lock:
                if len(reps) != len(self._replicas):
                    # Replica set changed: cached model->index residency is
                    # no longer valid.
                    self._model_replicas.clear()
                self._replicas = reps
                self._last_refresh = now
                for i in range(len(reps)):
                    self._outstanding.setdefault(i, 0)
        return self._replicas

    def _pick_replica(self):
        reps = self._get_replicas()
        if not reps:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} has no replicas")
        # Multiplexing: prefer the least-loaded replica that already has
        # this model resident (reference: router.py multiplexed routing).
        # Residency is tracked handle-side — recorded when a request for a
        # model is routed — not probed per request (a per-request RPC to
        # every replica would queue behind in-flight inference).
        if self._model_id and len(reps) > 1:
            with self._lock:
                cached = [i for i in self._model_replicas.get(
                    self._model_id, ()) if i < len(reps)]
                if cached:
                    idx = min(cached,
                              key=lambda i: self._outstanding.get(i, 0))
                    self._outstanding[idx] = self._outstanding.get(idx, 0) + 1
                    return idx, reps[idx]
        with self._lock:
            if len(reps) == 1:
                idx = 0
            else:
                a, b = random.sample(range(len(reps)), 2)
                idx = a if self._outstanding.get(a, 0) <= \
                    self._outstanding.get(b, 0) else b
            self._outstanding[idx] = self._outstanding.get(idx, 0) + 1
        return idx, reps[idx]

    def _done(self, idx):
        with self._lock:
            self._outstanding[idx] = max(0, self._outstanding.get(idx, 0) - 1)

    def _report_load(self):
        with self._lock:
            total = sum(self._outstanding.values())
        try:
            self._controller.record_handle_load.remote(
                self.deployment_name, total)
        except Exception:
            pass

    # -- request path --

    def remote(self, *args, **kwargs):
        if self._stream:
            return self._remote_stream(args, kwargs)
        resp = DeploymentResponse()
        if self._batchq is not None:
            self._batchq.add((args, kwargs), resp)
            return resp
        idx, replica = self._pick_replica()
        try:
            ref = replica.handle_request.remote(self._method, list(args), kwargs,
                                                self._model_id)
            if self._model_id:
                with self._lock:
                    self._model_replicas.setdefault(
                        self._model_id, set()).add(idx)
            resp._resolve_ref(ref)

            def retry_on_death(timeout):
                # The dead replica's cached set is stale: refresh and
                # resubmit. The controller needs a few health-check ticks
                # to replace dead replicas, so back off between attempts
                # (reference: handles retry system-level replica failures
                # until the deployment is available again).
                # timeout=None means wait indefinitely — same contract as
                # the normal result() path.
                deadline = (float("inf") if timeout is None
                            else time.monotonic() + timeout)
                last_err = None
                while time.monotonic() < deadline:
                    self._last_refresh = 0.0
                    try:
                        r_idx, r_replica = self._pick_replica()
                    except RuntimeError as e:  # no replicas yet
                        last_err = e
                        time.sleep(1.0)
                        continue
                    try:
                        budget = None if deadline == float("inf") else \
                            max(1.0, deadline - time.monotonic())
                        return ray_tpu.get(r_replica.handle_request.remote(
                            self._method, list(args), kwargs, self._model_id),
                            timeout=budget)
                    except ray_tpu.exceptions.ActorError as e:
                        last_err = e
                        time.sleep(1.0)
                    finally:
                        self._done(r_idx)
                raise last_err or TimeoutError("deployment retry timed out")

            resp._retry = retry_on_death
            with self._lock:
                self._inflight.append((idx, ref))
            self._ensure_reaper()
        except BaseException as e:  # noqa: BLE001
            resp._resolve_error(e)
            self._done(idx)
        self._report_load()
        return resp

    def _remote_stream(self, args, kwargs) -> DeploymentResponseGenerator:
        idx, replica = self._pick_replica()
        try:
            sid = ray_tpu.get(replica.start_stream.remote(
                self._method, list(args), kwargs, self._model_id), timeout=60)
        except BaseException:
            self._done(idx)
            raise
        return DeploymentResponseGenerator(self, idx, replica, sid)

    def _ensure_reaper(self):
        if self._reaper is None or not self._reaper.is_alive():
            self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
            self._reaper.start()

    def _reap_loop(self):
        while True:
            with self._lock:
                inflight = list(self._inflight)
            if not inflight:
                time.sleep(0.1)
                with self._lock:
                    if not self._inflight:
                        continue
                continue
            refs = [ref for _idx, ref in inflight]
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=0.2)
            except Exception:
                time.sleep(0.2)
                continue
            ready_set = set(ready)
            finished = [(i, r) for i, r in inflight if r in ready_set]
            if finished:
                with self._lock:
                    for item in finished:
                        if item in self._inflight:
                            self._inflight.remove(item)
                for idx, _r in finished:
                    self._done(idx)
            self._report_load()

    def _submit_batch(self, batch):
        idx, replica = self._pick_replica()
        try:
            ref = replica.handle_batch.remote(
                self._method, [item for item, _slot in batch])
            results = ray_tpu.get(ref, timeout=120)
            for (item, slot), value in zip(batch, results):
                slot._resolve_value(value)
        except BaseException as e:  # noqa: BLE001
            for _item, slot in batch:
                slot._resolve_error(e)
        finally:
            self._done(idx)


def _rebuild_handle(name, controller, method, model_id, batching=None):
    return DeploymentHandle(name, controller, method, batching=batching,
                            multiplexed_model_id=model_id)
