"""ServeController: the reconciling control loop.

Parity: reference python/ray/serve/_private/controller.py:87 (detached
controller actor; control loop :312 reconciles DeploymentState →
replica actors; autoscaling decision from handle-reported metrics
:221 + autoscaling_policy.py:117).
"""

from __future__ import annotations

import time

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.serve.deployment import AutoscalingConfig, ReplicaActor

CONTROLLER_NAME = "SERVE_CONTROLLER"


@ray_tpu.remote
class ServeController:
    def __init__(self):
        # name -> {config fields, replicas: [handle], target: int, ...}
        self.deployments: dict[str, dict] = {}
        self._last_scale: dict[str, float] = {}
        self._load: dict[str, tuple[float, float]] = {}  # name -> (ts, load)

    def deploy(self, name: str, callable_blob: bytes, init_args_blob: bytes,
               num_replicas: int, actor_options: dict,
               autoscaling: dict | None, user_config_blob: bytes | None):
        d = self.deployments.get(name)
        if d is None:
            d = self.deployments[name] = {
                "replicas": [], "version": 0}
        d["callable_blob"] = callable_blob
        d["init_args_blob"] = init_args_blob
        d["actor_options"] = actor_options or {}
        d["autoscaling"] = autoscaling
        d["user_config_blob"] = user_config_blob
        d["target"] = (autoscaling or {}).get("min_replicas", num_replicas) \
            if autoscaling else num_replicas
        d["version"] += 1
        self._reconcile(name)
        return True

    def _make_replica(self, d):
        init_args, init_kwargs = serialization.loads_func(d["init_args_blob"])
        user_config = (serialization.loads_func(d["user_config_blob"])
                       if d["user_config_blob"] else None)
        opts = dict(d["actor_options"])
        kwargs = {}
        if "num_cpus" in opts:
            kwargs["num_cpus"] = opts["num_cpus"]
        if "resources" in opts:
            kwargs["resources"] = opts["resources"]
        cls = ReplicaActor.options(**kwargs) if kwargs else ReplicaActor
        return cls.remote(d["callable_blob"], init_args, init_kwargs,
                          user_config)

    def _reconcile(self, name: str):
        d = self.deployments[name]
        while len(d["replicas"]) < d["target"]:
            d["replicas"].append(self._make_replica(d))
        while len(d["replicas"]) > d["target"]:
            victim = d["replicas"].pop()
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass

    def get_replicas(self, name: str):
        d = self.deployments.get(name)
        return list(d["replicas"]) if d else []

    def list_deployments(self):
        return {name: {"num_replicas": len(d["replicas"]),
                       "target": d["target"], "version": d["version"]}
                for name, d in self.deployments.items()}

    def record_handle_load(self, name: str, outstanding: float):
        """Handle-side queue metric → autoscaling decision (reference:
        controller.py:221 record_autoscaling_metrics +
        calculate_desired_num_replicas)."""
        self._load[name] = (time.time(), outstanding)
        d = self.deployments.get(name)
        if d is None or not d.get("autoscaling"):
            return
        asc = d["autoscaling"]
        target_per = asc.get("target_ongoing_requests", 2.0)
        desired = max(asc.get("min_replicas", 1),
                      min(asc.get("max_replicas", 4),
                          int((outstanding + target_per - 1) // target_per)))
        now = time.time()
        last = self._last_scale.get(name, 0.0)
        if desired > d["target"] and now - last > asc.get("upscale_delay_s", 0.5):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)
        elif desired < d["target"] and now - last > asc.get(
                "downscale_delay_s", 5.0):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)

    def delete_deployment(self, name: str):
        d = self.deployments.pop(name, None)
        if d:
            for r in d["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        return True

    def shutdown(self):
        for name in list(self.deployments):
            self.delete_deployment(name)
        return True
