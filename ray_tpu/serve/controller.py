"""ServeController: the reconciling control loop.

Parity: reference python/ray/serve/_private/controller.py:87 (detached
controller actor; control loop :312 reconciles DeploymentState →
replica actors; autoscaling decision from handle-reported metrics
:221 + autoscaling_policy.py:117).
"""

from __future__ import annotations

import time

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.serve.deployment import AutoscalingConfig, ReplicaActor

CONTROLLER_NAME = "SERVE_CONTROLLER"


@ray_tpu.remote
class ServeController:
    def __init__(self):
        import threading

        # name -> {config fields, replicas: [handle], target: int, ...}
        self.deployments: dict[str, dict] = {}
        self._last_scale: dict[str, float] = {}
        self._load: dict[str, tuple[float, float]] = {}  # name -> (ts, load)
        self._stop = threading.Event()
        # Guards replica-list mutation: the health loop runs on its own
        # thread, concurrent with actor methods (deploy/record_handle_load)
        # that also reconcile.
        self._rlock = threading.Lock()
        # Health-check loop: replace crashed replicas (reference: the
        # controller control loop at controller.py:312 reconciles
        # DeploymentState each tick; a dead replica actor is restarted).
        self._hc_thread = threading.Thread(target=self._health_loop,
                                           daemon=True)
        self._hc_thread.start()

    def _health_loop(self):
        # A busy replica answers slowly (requests are serviced in order),
        # so one slow/timed-out probe is not death: require 3 consecutive
        # failures, like the reference's consecutive health-check-failure
        # threshold (deployment_state.py replica health tracking).
        fails: dict[str, int] = {}
        while not self._stop.wait(2.0):
            # Purge counters for replicas no longer in any deployment
            # (actor ids are stable; id() would be recyclable).
            current = {r._actor_id.hex() for dd in self.deployments.values()
                       for r in dd["replicas"]}
            for k in list(fails):
                if k not in current:
                    del fails[k]
            for name in list(self.deployments):
                d = self.deployments.get(name)
                if d is None:
                    continue
                dead_ids = set()
                for r in list(d["replicas"]):
                    key = r._actor_id.hex()
                    try:
                        ray_tpu.get(r.health_check.remote(), timeout=10)
                        fails.pop(key, None)
                    except ray_tpu.exceptions.ActorDiedError:
                        dead_ids.add(key)
                        fails.pop(key, None)
                    except Exception:
                        fails[key] = fails.get(key, 0) + 1
                        if fails[key] >= 3:
                            dead_ids.add(key)
                            fails.pop(key, None)
                            try:
                                ray_tpu.kill(r)
                            except Exception:
                                pass
                if dead_ids:
                    with self._rlock:
                        # Drop only the replicas observed dead; replicas
                        # appended concurrently by deploy/scale-up survive.
                        d["replicas"] = [r for r in d["replicas"]
                                         if r._actor_id.hex() not in dead_ids]
                    try:
                        self._reconcile(name)
                    except Exception:
                        pass

    def deploy(self, name: str, callable_blob: bytes, init_args_blob: bytes,
               num_replicas: int, actor_options: dict,
               autoscaling: dict | None, user_config_blob: bytes | None,
               route_prefix: str | None = None):
        d = self.deployments.get(name)
        if d is None:
            d = self.deployments[name] = {
                "replicas": [], "version": 0}
        d["callable_blob"] = callable_blob
        d["init_args_blob"] = init_args_blob
        d["actor_options"] = actor_options or {}
        d["autoscaling"] = autoscaling
        d["user_config_blob"] = user_config_blob
        d["route_prefix"] = route_prefix if route_prefix is not None \
            else f"/{name}"
        d["target"] = (autoscaling or {}).get("min_replicas", num_replicas) \
            if autoscaling else num_replicas
        d["version"] += 1
        self._reconcile(name)
        # Redeploy with a changed user_config must reach the replicas that
        # already exist — reconcile only fixes the count (reference:
        # deployment_state reconfigures live replicas on config-only
        # updates instead of restarting them).
        if user_config_blob is not None:
            user_config = serialization.loads_func(user_config_blob)
            for r in list(d["replicas"]):
                try:
                    r.reconfigure.remote(user_config)
                except Exception:
                    pass
        return True

    def _make_replica(self, d):
        init_args, init_kwargs = serialization.loads_func(d["init_args_blob"])
        user_config = (serialization.loads_func(d["user_config_blob"])
                       if d["user_config_blob"] else None)
        opts = dict(d["actor_options"])
        kwargs = {}
        if "num_cpus" in opts:
            kwargs["num_cpus"] = opts["num_cpus"]
        if "resources" in opts:
            kwargs["resources"] = opts["resources"]
        cls = ReplicaActor.options(**kwargs) if kwargs else ReplicaActor
        return cls.remote(d["callable_blob"], init_args, init_kwargs,
                          user_config)

    def _reconcile(self, name: str):
        d = self.deployments[name]
        with self._rlock:
            while len(d["replicas"]) < d["target"]:
                d["replicas"].append(self._make_replica(d))
            victims = []
            while len(d["replicas"]) > d["target"]:
                victims.append(d["replicas"].pop())
        for victim in victims:
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass

    def get_replicas(self, name: str):
        d = self.deployments.get(name)
        return list(d["replicas"]) if d else []

    def route_table(self) -> dict:
        """{route_prefix: deployment_name} for proxy-side caching (the
        proxy does the longest-prefix match against this table)."""
        return {d.get("route_prefix") or f"/{name}": name
                for name, d in self.deployments.items()}

    def list_deployments(self):
        return {name: {"num_replicas": len(d["replicas"]),
                       "target": d["target"], "version": d["version"]}
                for name, d in self.deployments.items()}

    def record_handle_load(self, name: str, outstanding: float):
        """Handle-side queue metric → autoscaling decision (reference:
        controller.py:221 record_autoscaling_metrics +
        calculate_desired_num_replicas)."""
        self._load[name] = (time.time(), outstanding)
        d = self.deployments.get(name)
        if d is None or not d.get("autoscaling"):
            return
        asc = d["autoscaling"]
        target_per = asc.get("target_ongoing_requests", 2.0)
        desired = max(asc.get("min_replicas", 1),
                      min(asc.get("max_replicas", 4),
                          int((outstanding + target_per - 1) // target_per)))
        now = time.time()
        last = self._last_scale.get(name, 0.0)
        if desired > d["target"] and now - last > asc.get("upscale_delay_s", 0.5):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)
        elif desired < d["target"] and now - last > asc.get(
                "downscale_delay_s", 5.0):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)

    def delete_deployment(self, name: str):
        d = self.deployments.pop(name, None)
        if d:
            for r in d["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        return True

    def shutdown(self):
        self._stop.set()
        for name in list(self.deployments):
            self.delete_deployment(name)
        return True
