"""ServeController: the reconciling control loop.

Parity: reference python/ray/serve/_private/controller.py:87 (detached
controller actor; control loop :312 reconciles DeploymentState →
replica actors; autoscaling decision from handle-reported metrics
:221 + autoscaling_policy.py:117 with a look-back window), long_poll.py
LongPollHost:63 (held-connection config push to proxies/handles), and
deployment_state.py:1149 (versioned rolling updates with graceful
drain).
"""

from __future__ import annotations

import time
from collections import deque

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.serve.deployment import AutoscalingConfig, ReplicaActor

CONTROLLER_NAME = "SERVE_CONTROLLER"

# The controller must serve many parked long_poll calls CONCURRENTLY with
# deploys/health work; a max_concurrency=1 actor would deadlock the
# control plane behind the first parked poll.
CONTROLLER_CONCURRENCY = 32

# Parked long_poll calls may hold at most this many of the concurrency
# slots; excess pollers get an immediate empty reply (they degrade to
# fast re-polling) so control-plane RPCs always have free lanes.
MAX_PARKED_POLLS = 20


@ray_tpu.remote(max_concurrency=CONTROLLER_CONCURRENCY)
class ServeController:
    def __init__(self):
        import threading

        # name -> {config fields, replicas: [handle], rver: [int], ...}
        self.deployments: dict[str, dict] = {}
        self._last_scale: dict[str, float] = {}
        # name -> deque[(ts, load)] — look-back window for autoscaling
        # (reference: autoscaling_policy.py:117 averages over
        # look_back_period_s instead of acting on instantaneous gauges).
        self._load_samples: dict[str, deque] = {}
        self._stop = threading.Event()
        # Guards replica-list mutation: the health loop runs on its own
        # thread, concurrent with actor methods (deploy/record_handle_load)
        # that also reconcile.
        self._rlock = threading.Lock()
        # Long-poll state (reference: long_poll.py LongPollHost): key ->
        # monotonically-increasing version + current value; listeners park
        # on the condition until something they watch changes.
        self._poll_versions: dict[str, int] = {}
        self._poll_values: dict[str, object] = {}
        self._poll_cv = threading.Condition()
        self._poll_slots = threading.BoundedSemaphore(MAX_PARKED_POLLS)
        # Health-check loop: replace crashed replicas (reference: the
        # controller control loop at controller.py:312 reconciles
        # DeploymentState each tick; a dead replica actor is restarted).
        self._hc_thread = threading.Thread(target=self._health_loop,
                                           daemon=True)
        self._hc_thread.start()

    # ---------- long poll (reference: long_poll.py:63) ----------

    def _publish(self, key: str, value) -> None:
        with self._poll_cv:
            self._poll_versions[key] = self._poll_versions.get(key, 0) + 1
            self._poll_values[key] = value
            self._poll_cv.notify_all()

    def _publish_replicas(self, name: str) -> None:
        d = self.deployments.get(name)
        reps = list(d["replicas"]) if d else []
        self._publish(f"replicas:{name}", reps)

    def _publish_routes(self) -> None:
        self._publish("routes", self.route_table())

    def long_poll(self, known: dict, timeout_s: float = 10.0) -> dict:
        """Held-connection config push: blocks until any watched key has a
        version newer than the caller's, then returns {key: [version,
        value]}. Callers loop — this is the reference's
        LongPollHost.listen_for_change contract."""
        deadline = time.monotonic() + timeout_s
        parked = False
        try:
            with self._poll_cv:
                while True:
                    updates = {}
                    for key, ver in known.items():
                        cur = self._poll_versions.get(key, 0)
                        if cur > ver:
                            updates[key] = [cur, self._poll_values.get(key)]
                    if updates:
                        return updates
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop.is_set():
                        return {}
                    if not parked:
                        # Bounded parking: when every poll slot is taken,
                        # answer empty NOW instead of occupying a
                        # concurrency lane the control plane needs.
                        if not self._poll_slots.acquire(blocking=False):
                            return {}
                        parked = True
                    self._poll_cv.wait(remaining)
        finally:
            if parked:
                self._poll_slots.release()

    # ---------- health ----------

    def _probe_replicas(self, probes: list, fails: dict) -> set:
        """CONCURRENT health probes: one wait over all replicas instead of
        serial O(replicas x timeout) gets (reference: health checks fan
        out in deployment_state)."""
        dead = set()
        refs = []
        for key, r in probes:
            try:
                refs.append((key, r, r.health_check.remote()))
            except Exception:
                dead.add(key)
        if not refs:
            return dead
        ray_tpu.wait([ref for _, _, ref in refs],
                     num_returns=len(refs), timeout=10)
        for key, r, ref in refs:
            try:
                ray_tpu.get(ref, timeout=0.5)
                fails.pop(key, None)
            except ray_tpu.exceptions.ActorDiedError:
                dead.add(key)
                fails.pop(key, None)
            except Exception:
                fails[key] = fails.get(key, 0) + 1
                if fails[key] >= 3:
                    dead.add(key)
                    fails.pop(key, None)
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
        return dead

    def _health_loop(self):
        # A busy replica answers slowly (requests are serviced in order),
        # so one slow/timed-out probe is not death: require 3 consecutive
        # failures, like the reference's consecutive health-check-failure
        # threshold (deployment_state.py replica health tracking).
        fails: dict[str, int] = {}
        while not self._stop.wait(2.0):
            current = {r._actor_id.hex() for dd in self.deployments.values()
                       for r in dd["replicas"]}
            for k in list(fails):
                if k not in current:
                    del fails[k]
            for name in list(self.deployments):
                d = self.deployments.get(name)
                if d is None:
                    continue
                probes = [(r._actor_id.hex(), r) for r in list(d["replicas"])]
                dead_ids = self._probe_replicas(probes, fails)
                if dead_ids:
                    with self._rlock:
                        keep = [(r, v) for r, v in zip(d["replicas"], d["rver"])
                                if r._actor_id.hex() not in dead_ids]
                        d["replicas"] = [r for r, _ in keep]
                        d["rver"] = [v for _, v in keep]
                    try:
                        self._reconcile(name)
                    except Exception:
                        pass
                    self._publish_replicas(name)
                try:
                    self._autoscale_on_metrics(name, d)
                except Exception:
                    pass

    def _autoscale_on_metrics(self, name: str, d: dict):
        """Per-pool autoscaling on a REPLICA-REPORTED named metric
        (autoscaling.metric / target_value): each health tick polls every
        replica's report_metrics(), sums the named gauge, windows it over
        look_back_period_s, and reconciles toward ceil(avg / target).
        Deployments without `metric` keep the handle-side
        outstanding-request signal (record_handle_load)."""
        asc = d.get("autoscaling") or {}
        metric = asc.get("metric")
        target = asc.get("target_value")
        if not metric or not target:
            return
        refs = []
        for r in list(d["replicas"]):
            try:
                refs.append(r.report_metrics.remote())
            except Exception:
                pass
        if refs:
            ray_tpu.wait(refs, num_returns=len(refs), timeout=5)
        total = 0.0
        for ref in refs:
            try:
                total += float(
                    ray_tpu.get(ref, timeout=0.5).get(metric, 0.0))
            except Exception:
                pass
        now = time.time()
        samples = self._load_samples.setdefault(name, deque(maxlen=256))
        samples.append((now, total))
        look_back = asc.get("look_back_period_s", 10.0)
        window = [v for ts, v in samples if now - ts <= look_back]
        avg = sum(window) / max(1, len(window))
        desired = max(asc.get("min_replicas", 1),
                      min(asc.get("max_replicas", 4),
                          int(-(-avg // target))))
        last = self._last_scale.get(name, 0.0)
        if desired > d["target"] and \
                now - last > asc.get("upscale_delay_s", 0.5):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)
        elif desired < d["target"] and \
                now - last > asc.get("downscale_delay_s", 5.0):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)

    # ---------- deploy / reconcile / rolling update ----------

    def deploy(self, name: str, callable_blob: bytes, init_args_blob: bytes,
               num_replicas: int, actor_options: dict,
               autoscaling: dict | None, user_config_blob: bytes | None,
               route_prefix: str | None = None):
        d = self.deployments.get(name)
        if d is None:
            d = self.deployments[name] = {
                "replicas": [], "rver": [], "version": 0, "code_version": 0}
        code_changed = (
            d.get("callable_blob") != callable_blob
            or d.get("init_args_blob") != init_args_blob
            or (d.get("actor_options") or {}) != (actor_options or {}))
        d["callable_blob"] = callable_blob
        d["init_args_blob"] = init_args_blob
        d["actor_options"] = actor_options or {}
        d["autoscaling"] = autoscaling
        d["user_config_blob"] = user_config_blob
        d["route_prefix"] = route_prefix if route_prefix is not None \
            else f"/{name}"
        d["target"] = (autoscaling or {}).get("min_replicas", num_replicas) \
            if autoscaling else num_replicas
        d["version"] += 1
        if code_changed:
            d["code_version"] += 1
        self._reconcile(name)
        if code_changed and any(v != d["code_version"] for v in d["rver"]):
            # Versioned ROLLING update: replace old-code replicas one at a
            # time — start new, wait healthy, publish, drain old
            # (reference: deployment_state.py:1149 rolling updates with
            # graceful draining).
            self._rolling_update(name)
        elif user_config_blob is not None:
            # Config-only redeploy reconfigures LIVE replicas in place
            # (reference: lightweight user_config updates don't restart).
            user_config = serialization.loads_func(user_config_blob)
            for r in list(d["replicas"]):
                try:
                    r.reconfigure.remote(user_config)
                except Exception:
                    pass
        self._publish_routes()
        self._publish_replicas(name)
        return True

    def _make_replica(self, d):
        init_args, init_kwargs = serialization.loads_func(d["init_args_blob"])
        user_config = (serialization.loads_func(d["user_config_blob"])
                       if d["user_config_blob"] else None)
        opts = dict(d["actor_options"])
        kwargs = {}
        if "num_cpus" in opts:
            kwargs["num_cpus"] = opts["num_cpus"]
        if "resources" in opts:
            kwargs["resources"] = opts["resources"]
        if "max_concurrency" in opts:
            # Streaming engine replicas need concurrent lanes: a
            # prepare_drain that blocks until streams finish would
            # otherwise deadlock against the next_chunks pulls those
            # streams need to finish.
            kwargs["max_concurrency"] = opts["max_concurrency"]
        cls = ReplicaActor.options(**kwargs) if kwargs else ReplicaActor
        return cls.remote(d["callable_blob"], init_args, init_kwargs,
                          user_config)

    def _reconcile(self, name: str):
        d = self.deployments[name]
        with self._rlock:
            while len(d["replicas"]) < d["target"]:
                d["replicas"].append(self._make_replica(d))
                d["rver"].append(d["code_version"])
            victims = []
            while len(d["replicas"]) > d["target"]:
                victims.append(d["replicas"].pop())
                d["rver"].pop()
        if victims:
            import threading

            # Drain in the background: a downscale decision must not
            # stall the control plane for the drain duration.
            for victim in victims:
                threading.Thread(target=self._drain_and_kill,
                                 args=(victim,), daemon=True).start()
        self._publish_replicas(name)

    def _rolling_update(self, name: str):
        d = self.deployments[name]
        while True:
            with self._rlock:
                idx = next((i for i, v in enumerate(d["rver"])
                            if v != d["code_version"]), None)
                if idx is None:
                    return
                old = d["replicas"][idx]
            new = self._make_replica(d)
            try:
                # New replica must be HEALTHY before the old one leaves
                # the pool — this is what makes the update zero-downtime.
                ray_tpu.get(new.health_check.remote(), timeout=120)
            except Exception:
                try:
                    ray_tpu.kill(new)
                except Exception:
                    pass
                raise RuntimeError(
                    f"rolling update of {name!r} aborted: new replica "
                    f"failed its initial health check")
            import threading

            with self._rlock:
                # Re-locate by IDENTITY: the list may have shifted while
                # the new replica came up (health-loop removal,
                # autoscaling) — a stale index would swap out the wrong
                # replica.
                try:
                    cur = d["replicas"].index(old)
                except ValueError:
                    # Old replica already gone (died / scaled away):
                    # nothing to replace; drop the spare and re-check.
                    try:
                        ray_tpu.kill(new)
                    except Exception:
                        pass
                    continue
                d["replicas"][cur] = new
                d["rver"][cur] = d["code_version"]
            self._publish_replicas(name)
            # Drain in the background: the old replica is already out of
            # the routed set; blocking deploy() on its in-flight work
            # adds nothing to correctness (same policy as _reconcile).
            threading.Thread(target=self._drain_and_kill, args=(old,),
                             daemon=True).start()

    def _drain_and_kill(self, replica):
        """Graceful drain: give routers a beat to observe the published
        replica set, then wait for everything already queued on the
        replica (single execution lane => a sentinel call returning means
        all earlier-arrived requests finished), then kill. Stragglers that
        still raced a request in are resubmitted by the handle's
        replica-death retry path."""
        time.sleep(1.0)
        try:
            # Scale-in drain protocol: let the callable finish (or
            # evacuate) its in-flight streams before the kill — this is
            # what makes a decode-pool downscale lose zero requests.
            ray_tpu.get(replica.prepare_drain.remote(), timeout=300)
        except Exception:
            pass
        try:
            ray_tpu.get(replica.health_check.remote(), timeout=300)
        except Exception:
            pass
        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    def get_replicas(self, name: str):
        d = self.deployments.get(name)
        return list(d["replicas"]) if d else []

    def route_table(self) -> dict:
        """{route_prefix: deployment_name} for proxy-side caching (the
        proxy does the longest-prefix match against this table)."""
        return {d.get("route_prefix") or f"/{name}": name
                for name, d in self.deployments.items()}

    def list_deployments(self):
        return {name: {"num_replicas": len(d["replicas"]),
                       "target": d["target"], "version": d["version"],
                       "code_version": d["code_version"]}
                for name, d in self.deployments.items()}

    def record_handle_load(self, name: str, outstanding: float):
        """Handle-side queue metric → autoscaling decision over a
        look-back WINDOW (reference: controller.py:221
        record_autoscaling_metrics + BasicAutoscalingPolicy:117 averaging
        over look_back_period_s — instantaneous gauges flap under bursty
        load)."""
        now = time.time()
        d = self.deployments.get(name)
        if d is None or not d.get("autoscaling"):
            return
        asc = d["autoscaling"]
        if asc.get("metric"):
            # This pool scales on a replica-reported named metric polled
            # by the health loop; the handle-side queue signal would
            # fight it (and its samples would pollute the same window).
            return
        samples = self._load_samples.setdefault(name, deque(maxlen=256))
        samples.append((now, outstanding))
        look_back = asc.get("look_back_period_s", 10.0)
        window = [v for ts, v in samples if now - ts <= look_back]
        avg = sum(window) / max(1, len(window))
        target_per = asc.get("target_ongoing_requests", 2.0)
        desired = max(asc.get("min_replicas", 1),
                      min(asc.get("max_replicas", 4),
                          int((avg + target_per - 1) // target_per)))
        last = self._last_scale.get(name, 0.0)
        if desired > d["target"] and now - last > asc.get("upscale_delay_s", 0.5):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)
        elif desired < d["target"] and now - last > asc.get(
                "downscale_delay_s", 5.0):
            d["target"] = desired
            self._last_scale[name] = now
            self._reconcile(name)

    def delete_deployment(self, name: str):
        d = self.deployments.pop(name, None)
        if d:
            for r in d["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        self._publish_routes()
        self._publish(f"replicas:{name}", [])
        return True

    def shutdown(self):
        self._stop.set()
        with self._poll_cv:
            self._poll_cv.notify_all()
        for name in list(self.deployments):
            self.delete_deployment(name)
        return True
