"""Continuous-batching LLM inference engine for Serve.

The reference serves LLMs through external engines (vLLM-style servers
behind Serve deployments); this engine is native and TPU-shaped:

- **Static shapes everywhere.** One compiled prefill program per prompt
  bucket (power-of-two widths) and ONE compiled decode program for the
  whole slot batch, reused every tick — no recompilation as requests
  come and go.
- **Slot-based continuous batching.** The decode batch is a fixed set of
  `max_batch` slots; new requests prefill into a free slot mid-flight
  while other slots keep decoding (the continuous-batching idea:
  admission does not wait for the batch to drain).
- **Per-slot KV caches with per-slot write offsets** via `jax.vmap` of
  the single-sequence decode step — each slot advances at its own
  position, which a plain batched `dynamic_update_slice` (one offset for
  all rows) cannot express.
- **Streaming.** `submit()` returns a handle whose iterator yields tokens
  as they are produced; `LLMDeployment` plugs that into Serve's
  generator-streaming path (`handle.options(stream=True)` / `?stream=1`).

- **Paged KV (page_size > 0).** Slots share one pool of fixed-size KV
  pages per layer (vLLM block tables, TPU-shaped: the scalar-prefetch
  pallas kernel in ops/paged_attention.py attends over scattered pages;
  PageAllocator manages the free list host-side). HBM is bounded by
  `kv_pool_tokens` RESIDENT tokens, not max_len x slots — admission
  defers requests when the pool is dry and pages return to the free
  list the moment a stream completes. page_size=0 keeps the dense
  per-slot max_len caches.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ray_tpu.models.generate import SamplingParams
from ray_tpu.models.llama import LlamaConfig, LlamaModel, init_kv_caches


@dataclass
class _Slot:
    request: "RequestHandle | None" = None
    generated: int = 0
    # Chunked prefill in progress: the full prompt and how much of it has
    # been written into this slot's KV cache so far. None = decoding.
    prefill_prompt: "object" = None
    prefill_pos: int = 0
    # Paged mode: allocator key owning this slot's pages.
    seq_id: str = ""
    # Every token this stream has generated (including ones still queued
    # in the handle). A drain snapshot ships this so a resumed stream can
    # re-deliver exactly the tokens the consumer never received.
    history: list = field(default_factory=list)


class _Prefilled:
    """Admission payload for a request whose prefill ran in ANOTHER
    engine (the disaggregated prefill pool, or a resume after a drain
    evacuation): the per-layer KV prefix plus the decode cursor."""

    __slots__ = ("kv_layers", "token", "prompt_len", "lens", "generated",
                 "history", "emit_first")

    def __init__(self, kv_layers, token, prompt_len, lens, generated,
                 history, emit_first):
        self.kv_layers = kv_layers  # [(k, v)] per layer, (Hkv, L, D)
        self.token = int(token)      # next decode input (last sampled)
        self.prompt_len = int(prompt_len)
        self.lens = int(lens)        # valid KV entries
        self.generated = int(generated)
        self.history = list(history or [])
        self.emit_first = bool(emit_first)


class RequestHandle:
    """Client-side stream of generated tokens for one request.

    The token queue is BOUNDED (`max_buffered`): a consumer that stops
    draining while decode keeps producing parks the producing slot
    (backpressure) instead of growing host memory without limit."""

    def __init__(self, prompt_len: int, sampling: SamplingParams,
                 max_buffered: int = 256, tag: str = ""):
        self.prompt_len = prompt_len
        self.sampling = sampling
        self.tag = tag  # router-visible stream key (disagg resume)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, max_buffered))
        self._done = threading.Event()
        self._submit_ts = time.monotonic()
        self.error: Exception | None = None

    def _offer(self, tok: int) -> bool:
        """Non-blocking enqueue; False = consumer backlog full. The
        engine parks the slot on False — it must never block its loop
        on a slow consumer."""
        try:
            self._q.put_nowait(tok)
            return True
        except queue.Full:
            return False

    def _finish(self, error: Exception | None = None) -> None:
        if error is not None and self.error is None:
            self.error = error
        self._done.set()

    def backlog_full(self) -> bool:
        return self._q.full()

    def __iter__(self):
        while True:
            try:
                yield self._q.get(timeout=0.05)
                continue
            except queue.Empty:
                pass
            if self._done.is_set():
                # Drain tokens that raced the done flag: _finish is
                # ordered after the final _offer, but this iterator may
                # observe the event before emptying the queue.
                while True:
                    try:
                        yield self._q.get_nowait()
                    except queue.Empty:
                        break
                if self.error is not None:
                    raise self.error
                return

    def tokens(self) -> list[int]:
        """Block until completion; all tokens as a list."""
        return list(self)


class LLMEngine:
    """Slot-based continuous-batching engine over a Llama-family model."""

    def __init__(self, cfg: LlamaConfig, params, *, max_batch: int = 4,
                 max_len: int = 1024, decode_chunk: int = 8,
                 prefill_chunk: int = 0, rng_seed: int = 0,
                 page_size: int = 0, kv_pool_tokens: int = 0,
                 use_device_plane: bool = True, stream_buffer: int = 256):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self.params = params
        self.max_batch = max_batch
        self.max_len = max_len
        # Per-stream token-queue bound: a non-draining consumer parks its
        # slot (backpressure) once this many tokens are buffered.
        self._stream_buffer = max(1, stream_buffer)
        # Prefill→decode KV handoff rides the device object plane
        # (_private/device_objects.py): the freshly prefilled per-request
        # KV is pinned, resolved by decode over the cheapest route
        # (same-process → zero-copy handover of the live arrays), and
        # unpinned — pinned-KV bytes and handoff counts are observable
        # through the plane's gauges. Fails open: any plane error falls
        # back to the direct in-memory handoff.
        self.use_device_plane = use_device_plane
        # Paged KV mode (page_size > 0): admission is bounded by POOL
        # pages (resident tokens), not slot count x max_len.
        self.page_size = page_size
        if page_size:
            if max_len % page_size:
                raise ValueError(
                    f"max_len={max_len} must be a multiple of "
                    f"page_size={page_size}")
            if prefill_chunk:
                raise ValueError(
                    "chunked prefill is not supported in paged mode")
        # Steps per compiled decode call: one host sync per CHUNK, not per
        # token (dispatch/fetch latency dominates single-token decode —
        # dramatically so through a tunneled device). Admission waits at
        # most one chunk; tokens stream with chunk granularity.
        self.decode_chunk = max(1, decode_chunk)
        # >0: prompts longer than this prefill in chunks INTERLEAVED with
        # decode ticks, so one long prompt cannot stall every in-flight
        # stream for its whole prefill. 0: whole-prompt bucketed prefill.
        self.prefill_chunk = prefill_chunk
        self.model = LlamaModel(cfg)
        self._jax, self._jnp = jax, jnp
        self._rng = jax.random.PRNGKey(rng_seed)

        model = self.model

        # ---- compiled programs ------------------------------------------

        max_len_ = max_len
        cfg_ = cfg

        @jax.jit
        def prefill_one(params, tokens):
            # tokens: (1, bucket) right-padded. Cache entries past the true
            # prompt length hold garbage, but decode masks keys by position
            # (kpos <= qpos) and overwrites index `cache_len` before each
            # attention, so they are never attended.
            positions = jnp.arange(tokens.shape[1])[None, :]
            caches1 = init_kv_caches(cfg_, 1, max_len_)
            logits, new = model.apply(params, tokens, positions,
                                      kv_caches=caches1)
            return logits[0], [(k[0], v[0]) for k, v, _l in new]

        import functools

        @functools.partial(jax.jit, donate_argnums=(3,))
        def prefill_chunk(params, tokens, start, kv_full, slot):
            # One CHUNK of a long prompt: tokens (1, chunk) at absolute
            # positions start..start+chunk, KV written at the same offset
            # of slot `slot`'s cache. Gather/scatter of the slot row stays
            # INSIDE the jit with the full cache donated, so a chunk costs
            # one row update, not a full multi-slot cache copy per tick.
            C = tokens.shape[1]
            positions = start + jnp.arange(C)[None, :]
            caches1 = [
                (jax.lax.dynamic_slice_in_dim(k, slot, 1, axis=0),
                 jax.lax.dynamic_slice_in_dim(v, slot, 1, axis=0), start)
                for k, v in kv_full]
            logits, new = model.apply(params, tokens, positions,
                                      kv_caches=caches1)
            out_kv = [
                (jax.lax.dynamic_update_slice_in_dim(kf, kn, slot, axis=0),
                 jax.lax.dynamic_update_slice_in_dim(vf, vn, slot, axis=0))
                for (kf, vf), (kn, vn, _l) in zip(kv_full, new)]
            return logits[0], out_kv

        self._prefill_chunk = prefill_chunk

        def _decode_one(params, token, pos, kv, lens):
            # One sequence: token (), pos (), kv list of ((Hkv,L,D) k, v),
            # lens () — the slot's private write offset.
            caches1 = [(k[None], v[None], lens) for k, v in kv]
            logits, new = model.apply(params, token[None, None],
                                      pos[None, None], kv_caches=caches1)
            return logits[0, 0], [(k[0], v[0]) for k, v, _l in new]

        # vmap: slots advance at DIFFERENT offsets in the same program.
        decode_step = jax.vmap(_decode_one, in_axes=(None, 0, 0, 0, 0))

        V = cfg.vocab_size

        def _sample(logits, temps, top_ks, top_ps, rng):
            # Per-slot temperature / top-k / top-p, fully vectorized
            # (matches models/generate.sample_logits semantics per row;
            # top_ks==0 and top_ps==1 disable the truncations).
            greedy = jnp.argmax(logits, axis=-1)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            sorted_l = jnp.sort(scaled, axis=-1)[:, ::-1]
            k_idx = jnp.clip(jnp.where(top_ks > 0, top_ks, V) - 1, 0, V - 1)
            kth = jnp.take_along_axis(sorted_l, k_idx[:, None], axis=-1)
            scaled = jnp.where(scaled < kth, -1e30, scaled)
            probs = jax.nn.softmax(sorted_l, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            cut_idx = jnp.clip(jnp.sum(cum < top_ps[:, None], axis=-1), 0, V - 1)
            cutoff = jnp.take_along_axis(sorted_l, cut_idx[:, None], axis=-1)
            scaled = jnp.where(scaled < cutoff, -1e30, scaled)
            sampled = jax.random.categorical(rng, scaled, axis=-1)
            return jnp.where(temps <= 0.0, greedy, sampled)

        K = self.decode_chunk

        def decode_chunk_fn(params, token, pos, kv, lens, temps, top_ks,
                            top_ps, base_rng):
            # K decode steps in one program (lax.scan): sampling happens
            # in-device, so only the (K, B) token block crosses to host.
            def body(carry, i):
                token, pos, kv, lens = carry
                logits, kv = decode_step(params, token, pos, kv, lens)
                tok = _sample(logits, temps, top_ks, top_ps,
                              jax.random.fold_in(base_rng, i))
                return (tok, pos + 1, kv, lens + 1), tok

            (token, pos, kv, lens), toks = jax.lax.scan(
                body, (token, pos, kv, lens), jnp.arange(K))
            return toks, kv  # toks: (K, B)

        # Donating the caches makes each chunk update KV in place instead
        # of copying the full (B,Hkv,L,D)·2·layers working set through HBM.
        self._decode_chunk_fn = jax.jit(decode_chunk_fn, donate_argnums=(3,))
        self._sample = jax.jit(_sample)
        self._prefill_one = prefill_one

        # ---- paged-mode programs ----------------------------------------

        if page_size:
            from ray_tpu.models.llama import PagedKVCache
            from ray_tpu.ops.paged_attention import PageAllocator

            # Overshoot margin: a chunk of K steps may run up to K-1
            # tokens past a stream's max_new before the host notices eos.
            pool_tokens = kv_pool_tokens or max_batch * (max_len + K)
            self._np_pages = -(-(max_len + K) // page_size)  # table width
            self._num_pages = -(-pool_tokens // page_size) + 1  # + dummy
            self._tables = None  # created by _init_paged_state
            self._init_paged_state()

            def decode_chunk_paged(params, token, pos, pools, tables, lens,
                                   temps, top_ks, top_ps, base_rng):
                def body(carry, i):
                    token, pos, pools, lens = carry
                    caches = [PagedKVCache(k, v, tables, lens)
                              for (k, v) in pools]
                    logits, new = model.apply(params, token[:, None],
                                              pos[:, None], kv_caches=caches)
                    pools2 = [(c.k_pool, c.v_pool) for c in new]
                    tok = _sample(logits[:, 0], temps, top_ks, top_ps,
                                  jax.random.fold_in(base_rng, i))
                    return (tok, pos + 1, pools2, lens + 1), tok

                (token, pos, pools, lens), toks = jax.lax.scan(
                    body, (token, pos, pools, lens), jnp.arange(K))
                return toks, pools  # toks: (K, B)

            self._decode_chunk_paged = jax.jit(decode_chunk_paged,
                                               donate_argnums=(3,))

            ps_ = page_size

            @functools.partial(jax.jit, donate_argnums=(0,))
            def write_prompt_pages(pools, kv_one, page_ids):
                # Scatter a bucketed prefill's (Hkv, max_len, D) caches
                # into pool pages (pool layout (P, Hkv, page, D)).
                # page_ids rows past the prompt point at the dummy page
                # (garbage there is fine).
                out = []
                for (kp, vp), (k1, v1) in zip(pools, kv_one):
                    Hkv_, L_, D_ = k1.shape
                    kpg = k1.reshape(Hkv_, L_ // ps_, ps_, D_).transpose(
                        1, 0, 2, 3)
                    vpg = v1.reshape(Hkv_, L_ // ps_, ps_, D_).transpose(
                        1, 0, 2, 3)
                    out.append((kp.at[page_ids].set(kpg),
                                vp.at[page_ids].set(vpg)))
                return out

            self._write_prompt_pages = write_prompt_pages

            # ---- batched prefill admission --------------------------------
            # Sequential slot prefills dominate end-to-end serving at
            # large batch (each is a full program dispatch; measured on a
            # real v5e in BENCH_NOTES.md). When several same-bucket
            # requests are pending, ONE (W, bucket) prefill serves all of
            # them. W is FIXED (padding with rows that scatter into the
            # dummy page) so exactly one extra program per bucket
            # compiles, regardless of arrival pattern.
            self._batch_prefill_width = min(8, max_batch)

            @jax.jit
            def prefill_many(params, tokens, last_idx):
                # tokens: (W, bucket) right-padded; last_idx: (W,) index
                # of each row's last prompt token. Returns the last-token
                # logits row per sequence (gathered INSIDE jit: the full
                # (W, bucket, vocab) logits never reach the host) and the
                # per-layer (W, Hkv, L, D) caches.
                positions = jnp.arange(tokens.shape[1])[None, :]
                caches = init_kv_caches(cfg_, tokens.shape[0], max_len_)
                logits, new = model.apply(params, tokens, positions,
                                          kv_caches=caches)
                last = jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)[:, 0]
                return last, [(k, v) for k, v, _l in new]

            self._prefill_many = prefill_many

            @functools.partial(jax.jit, donate_argnums=(0,))
            def write_prompt_pages_many(pools, kv_many, page_ids):
                # Batched variant of write_prompt_pages: kv_many per
                # layer (W, Hkv, L, D), page_ids (W, L/ps). Rows flatten
                # into one scatter; padding rows target the dummy page.
                out = []
                flat = page_ids.reshape(-1)
                for (kp, vp), (k1, v1) in zip(pools, kv_many):
                    W_, Hkv_, L_, D_ = k1.shape
                    kpg = k1.reshape(W_, Hkv_, L_ // ps_, ps_, D_) \
                        .transpose(0, 2, 1, 3, 4) \
                        .reshape(-1, Hkv_, ps_, D_)
                    vpg = v1.reshape(W_, Hkv_, L_ // ps_, ps_, D_) \
                        .transpose(0, 2, 1, 3, 4) \
                        .reshape(-1, Hkv_, ps_, D_)
                    out.append((kp.at[flat].set(kpg),
                                vp.at[flat].set(vpg)))
                return out

            self._write_prompt_pages_many = write_prompt_pages_many
            self._deferred: list = []  # pool-dry admissions, FIFO retry

        # ---- engine state (host-managed; device caches stacked by slot) --

        if page_size:
            self._kv = None  # paged mode: pools above replace slot caches
        else:
            proto = init_kv_caches(cfg, max_batch, max_len)
            self._kv = [(k, v) for k, v, _l in proto]  # [(B,Hkv,L,D)] / layer
        self._lens = np.zeros(max_batch, np.int32)
        self._token = np.zeros(max_batch, np.int32)
        self._pos = np.zeros(max_batch, np.int32)
        self._temps = np.zeros(max_batch, np.float32)
        self._topks = np.zeros(max_batch, np.int32)
        self._topps = np.ones(max_batch, np.float32)
        self._slots = [_Slot() for _ in range(max_batch)]
        self._prefill_rr = 0  # round-robin cursor over prefilling slots
        self._pending: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        # Drain quiesce handshake: _quiesce asks the loop to pause at a
        # tick boundary; the loop acks via _quiet, after which slot/KV
        # state is stable for snapshot_active_streams().
        self._quiesce = threading.Event()
        self._quiet = threading.Event()
        # Named metrics for the per-pool autoscaler + bench surface.
        self._ttft = deque(maxlen=256)  # seconds, submit -> first token
        self._parked_events = 0  # backpressure: offers rejected (q full)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    # ---- public API ------------------------------------------------------

    def submit(self, prompt_tokens, sampling: SamplingParams | None = None,
               tag: str = "") -> RequestHandle:
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        sp = sampling or SamplingParams()
        if len(prompt) + sp.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new_tokens({sp.max_new_tokens})"
                f" exceeds engine max_len={self.max_len}")
        if self.page_size:
            need = self._alloc.pages_needed(
                len(prompt) + sp.max_new_tokens + self.decode_chunk)
            if need > self._alloc.num_pages - 1:  # -1: dummy page
                raise ValueError(
                    f"request needs {need} KV pages but the pool holds "
                    f"{self._alloc.num_pages - 1}; raise kv_pool_tokens")
        handle = RequestHandle(len(prompt), sp,
                               max_buffered=self._stream_buffer, tag=tag)
        self._pending.put((prompt, handle))
        return handle

    def submit_prefilled(self, pack: _Prefilled,
                         sampling: SamplingParams | None = None,
                         tag: str = "") -> RequestHandle:
        """Admit a request whose prefill ran elsewhere (disaggregated
        prefill pool, or a drain-evacuated stream being resumed): the
        KV prefix lands in a free slot and decoding continues from
        `pack.token` without re-running prefill here."""
        sp = sampling or SamplingParams()
        budget = sp.max_new_tokens - pack.generated
        if budget <= 0:
            raise ValueError("prefilled request has no decode budget left")
        if pack.lens + budget > self.max_len:
            raise ValueError(
                f"kv_len({pack.lens}) + remaining({budget}) exceeds "
                f"engine max_len={self.max_len}")
        handle = RequestHandle(pack.prompt_len, sp,
                               max_buffered=self._stream_buffer, tag=tag)
        self._pending.put((pack, handle))
        return handle

    def generate(self, prompt_tokens,
                 sampling: SamplingParams | None = None) -> list[int]:
        return self.submit(prompt_tokens, sampling).tokens()

    def num_active(self) -> int:
        return sum(1 for s in self._slots if s.request is not None)

    def tokens_in_flight(self) -> int:
        """Remaining decode budget across active streams — the decode
        pool's autoscaling signal."""
        total = 0
        for st in self._slots:
            h = st.request
            if h is not None:
                total += max(0, h.sampling.max_new_tokens - st.generated)
        return total

    def queue_depth(self) -> int:
        return self._pending.qsize() + len(getattr(self, "_deferred", []))

    def report_metrics(self) -> dict:
        ttft = sorted(self._ttft)
        pick = lambda q: ttft[min(len(ttft) - 1,  # noqa: E731
                                  int(q * len(ttft)))] if ttft else 0.0
        return {
            "queue_depth": float(self.queue_depth()),
            "tokens_in_flight": float(self.tokens_in_flight()),
            "active_streams": float(self.num_active()),
            "parked_events": float(self._parked_events),
            "ttft_p50_ms": pick(0.5) * 1e3,
            "ttft_p99_ms": pick(0.99) * 1e3,
        }

    def quiesce_for_drain(self, timeout: float = 10.0) -> bool:
        """Pause the loop at a tick boundary so slot/KV state is stable
        for snapshot_active_streams(). Returns True once the loop acked."""
        self._quiesce.set()
        return self._quiet.wait(timeout)

    def resume(self) -> None:
        self._quiesce.clear()
        self._quiet.clear()

    def snapshot_active_streams(self) -> dict:
        """Host-side snapshot of every decoding stream — caller must
        quiesce first. Keyed by the handle's tag; each value holds the
        trimmed per-layer KV (numpy) and the full decode cursor, enough
        to rebuild the stream via submit_prefilled on another replica."""
        out: dict = {}
        for i, st in enumerate(self._slots):
            h = st.request
            if h is None or st.prefill_prompt is not None:
                continue
            L = int(self._lens[i])
            kv = []
            if self.page_size:
                ps = self.page_size
                n = -(-L // ps)
                row = self._tables[i][:n]
                for kp, vp in self._pools:
                    Hkv, D = kp.shape[1], kp.shape[3]
                    k = np.asarray(kp[row]).transpose(1, 0, 2, 3).reshape(
                        Hkv, n * ps, D)[:, :L]
                    v = np.asarray(vp[row]).transpose(1, 0, 2, 3).reshape(
                        Hkv, n * ps, D)[:, :L]
                    kv.append((k, v))
            else:
                for kf, vf in self._kv:
                    kv.append((np.asarray(kf[i, :, :L]),
                               np.asarray(vf[i, :, :L])))
            sp = h.sampling
            out[h.tag or f"slot{i}"] = {
                "kv": kv,
                "prompt_len": int(h.prompt_len),
                "lens": L,
                "token": int(self._token[i]),
                "generated": int(st.generated),
                "history": list(st.history),
                "sampling": {"max_new_tokens": sp.max_new_tokens,
                             "temperature": sp.temperature,
                             "top_k": sp.top_k, "top_p": sp.top_p,
                             "eos_token": sp.eos_token},
            }
        return out

    def shutdown(self):
        self._stop.set()
        self._thread.join(5.0)
        self._fail_all(RuntimeError("engine shut down"))

    def _fail_all(self, err: Exception):
        """Unblock every waiter: active slots, deferred and queued requests."""
        for i, st in enumerate(self._slots):
            if st.request is not None:
                st.request._finish(err)
                st.request = None
            st.prefill_prompt = None
            self._free_slot_pages(i)
        for _prompt, handle in getattr(self, "_deferred", []):
            handle._finish(err)
        if self.page_size:
            self._deferred.clear()
        while True:
            try:
                _prompt, handle = self._pending.get_nowait()
            except queue.Empty:
                break
            handle._finish(err)

    # ---- engine loop -----------------------------------------------------

    def _topks_arr(self):
        return self._jnp.asarray(self._topks)

    def _topps_arr(self):
        return self._jnp.asarray(self._topps)

    def _bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _admit(self, prompt: np.ndarray, handle: RequestHandle):
        """DENSE-mode admission. Paged admissions go through
        _reserve_paged + _admit_paged_group in the loop instead."""
        assert not self.page_size
        jnp = self._jnp
        slot = next(i for i, s in enumerate(self._slots) if s.request is None)
        if isinstance(prompt, _Prefilled):
            self._admit_prefilled_dense(slot, prompt, handle)
            return
        # Chunked only when the chunk GRID fits the cache: the final
        # chunk's write window [start, start+C) must not run past max_len,
        # where dynamic_update_slice clamping would silently relocate it
        # over already-prefilled KV. Otherwise the bucketed whole-prompt
        # path (whose write window is exactly the bucket) handles it.
        C = self.prefill_chunk
        grid_fits = C and -(-len(prompt) // C) * C <= self.max_len
        if C and len(prompt) > C and grid_fits:
            # Chunked path: bookkeeping only; the loop advances one chunk
            # per tick. Point the slot's decode-write offset at the last
            # cache index so the shared decode program's garbage writes
            # for this still-prefilling slot cannot land inside the
            # region being prefilled (that index is overwritten before
            # any legitimate attention reaches it).
            st = self._slots[slot]
            st.request = handle
            st.generated = 0
            st.prefill_prompt = prompt
            st.prefill_pos = 0
            st.history = []
            self._lens[slot] = self.max_len - 1
            self._temps[slot] = handle.sampling.temperature
            self._topks[slot] = handle.sampling.top_k
            self._topps[slot] = handle.sampling.top_p
            return
        bucket = self._bucket(len(prompt))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(prompt)] = prompt
        logits, kv_one = self._prefill_one(self.params, jnp.asarray(padded))
        kv_one = self._device_handoff(kv_one)
        # Write the slot row of every layer cache + first sampled token.
        for li, (k_full, v_full) in enumerate(self._kv):
            k_one, v_one = kv_one[li]
            self._kv[li] = (k_full.at[slot].set(k_one),
                            v_full.at[slot].set(v_one))
        self._commit_first_token(slot, handle,
                                 logits[len(prompt) - 1], len(prompt))

    def _device_handoff(self, kv):
        """Hand the prefill KV cache to decode as a device object:
        same-process resolution returns the SAME live arrays (zero copy)
        while ticking the plane's pinned-HBM gauge and in_process
        counter — the serve hot path's first device-plane consumer."""
        if not self.use_device_plane:
            return kv
        try:
            from ray_tpu._private import device_objects

            return device_objects.local_handoff("llm-prefill-kv", kv)
        except Exception:
            return kv

    def _commit_first_token(self, slot: int, handle: RequestHandle,
                            first_logits, prompt_len: int):
        """Shared prefill->decode handoff: sample the first token and
        commit all per-slot decode state (one protocol, dense AND paged)."""
        self._rng, srng = self._jax.random.split(self._rng)
        sp = handle.sampling
        tok = int(np.asarray(self._sample(
            first_logits[None], np.float32([sp.temperature]),
            np.int32([sp.top_k]), np.float32([sp.top_p]), srng))[0])
        self._commit_token(slot, handle, tok, prompt_len)

    def _commit_token(self, slot: int, handle: RequestHandle, tok: int,
                      prompt_len: int):
        """Commit an already-sampled first token + per-slot decode state
        (batched admission samples a whole group in one dispatch)."""
        sp = handle.sampling
        self._lens[slot] = prompt_len
        self._pos[slot] = prompt_len
        self._token[slot] = tok
        self._temps[slot] = sp.temperature
        self._topks[slot] = sp.top_k
        self._topps[slot] = sp.top_p
        st = self._slots[slot]
        st.request = handle
        st.generated = 0
        st.prefill_prompt = None
        st.history = []
        self._ttft.append(time.monotonic() - handle._submit_ts)
        self._emit(slot, tok)

    def _commit_prefilled(self, slot: int, handle: RequestHandle,
                          pack: _Prefilled):
        """Commit decode state for an externally prefilled stream. A
        fresh handoff (emit_first=True) behaves like _commit_token with
        the prefill pool's sampled first token; a resume carries the
        full history/cursor and emits nothing until decode advances."""
        sp = handle.sampling
        self._lens[slot] = pack.lens
        self._pos[slot] = pack.lens
        self._token[slot] = pack.token
        self._temps[slot] = sp.temperature
        self._topks[slot] = sp.top_k
        self._topps[slot] = sp.top_p
        st = self._slots[slot]
        st.request = handle
        st.generated = pack.generated
        st.prefill_prompt = None
        st.history = list(pack.history)
        if pack.emit_first:
            self._ttft.append(time.monotonic() - handle._submit_ts)
            self._emit(slot, pack.token)

    def _admit_prefilled_dense(self, slot: int, pack: _Prefilled,
                               handle: RequestHandle):
        """Land an external KV prefix in a dense slot row. Cache entries
        past `pack.lens` keep whatever garbage they hold — decode masks
        kpos<=qpos and overwrites index lens before attending."""
        jnp = self._jnp
        L = pack.lens
        for li, (k_full, v_full) in enumerate(self._kv):
            k1, v1 = pack.kv_layers[li]
            k1 = jnp.asarray(np.asarray(k1)[:, :L], self.cfg.dtype)
            v1 = jnp.asarray(np.asarray(v1)[:, :L], self.cfg.dtype)
            self._kv[li] = (k_full.at[slot, :, :L, :].set(k1),
                            v_full.at[slot, :, :L, :].set(v1))
        self._commit_prefilled(slot, handle, pack)

    def _admit_prefilled_paged(self, slot: int, seq_id: str,
                               pack: _Prefilled, handle: RequestHandle):
        """Scatter an external KV prefix into this sequence's reserved
        pages. The prefix is padded up to the engine bucket (a page
        multiple) so write_prompt_pages compiles one variant per bucket,
        not one per arbitrary kv length; pad rows scatter into the dummy
        page, never a page a live sequence owns."""
        jnp = self._jnp
        ps = self.page_size
        Lb = -(-max(self._bucket(pack.lens), ps) // ps) * ps
        n_real = -(-pack.lens // ps)
        row = np.asarray(self._alloc.table(seq_id, self._np_pages))
        page_ids = np.full(Lb // ps, self._dummy_page, np.int32)
        page_ids[:n_real] = row[:n_real]
        kv_pad = []
        for k1, v1 in pack.kv_layers:
            k1 = np.asarray(k1)[:, :pack.lens]
            v1 = np.asarray(v1)[:, :pack.lens]
            Hkv, L, D = k1.shape
            kp = np.zeros((Hkv, Lb, D), k1.dtype)
            vp = np.zeros((Hkv, Lb, D), v1.dtype)
            kp[:, :L] = k1
            vp[:, :L] = v1
            kv_pad.append((jnp.asarray(kp, self.cfg.dtype),
                           jnp.asarray(vp, self.cfg.dtype)))
        self._pools = self._write_prompt_pages(
            self._pools, kv_pad, jnp.asarray(page_ids))
        self._tables[slot] = row
        self._commit_prefilled(slot, handle, pack)

    def _reserve_paged(self, slot: int, prompt: np.ndarray,
                       handle: RequestHandle) -> str:
        """Reserve pages for the stream's WHOLE lifetime (prompt +
        max_new + chunk overshoot) up front, so decode can never fail
        mid-stream on an empty pool; MemoryError here defers the request
        instead (admission control by resident tokens)."""
        sp = handle.sampling
        st = self._slots[slot]
        seq_id = f"slot{slot}-{id(handle):x}"
        if isinstance(prompt, _Prefilled):
            need = prompt.lens + (sp.max_new_tokens - prompt.generated) \
                + self.decode_chunk
        else:
            need = len(prompt) + sp.max_new_tokens + self.decode_chunk
        self._alloc.allocate(seq_id, need)  # MemoryError -> caller defers
        st.seq_id = seq_id
        return seq_id

    def _admit_paged_group(self, cands: list) -> None:
        """Prefill reserved candidates, batching same-bucket requests
        through the fixed-width prefill_many program (one dispatch for
        up to _batch_prefill_width streams). Singleton groups keep the
        single-sequence program. cands: (slot, seq_id, prompt, handle)
        with pages already reserved."""
        jnp = self._jnp
        # Externally prefilled streams skip the prefill programs entirely:
        # their KV prefix scatters straight into the reserved pages.
        for slot, seq_id, pack, handle in \
                [c for c in cands if isinstance(c[2], _Prefilled)]:
            try:
                self._admit_prefilled_paged(slot, seq_id, pack, handle)
            except BaseException as e:
                self._free_slot_pages(slot)
                handle._finish(e)
        cands = [c for c in cands if not isinstance(c[2], _Prefilled)]
        groups: dict = {}
        for c in cands:
            bucket = max(self._bucket(len(c[2])), self.page_size)
            groups.setdefault(bucket, []).append(c)
        for bucket, group in groups.items():
            while group:
                chunk = group[: self._batch_prefill_width]
                group = group[len(chunk):]
                if len(chunk) == 1:
                    slot, seq_id, prompt, handle = chunk[0]
                    try:
                        logits = self._prefill_into_pages(slot, seq_id,
                                                          prompt)
                        # _commit_first_token dispatches _sample: it must
                        # be covered too, or a transient device error
                        # kills the engine thread and strands every
                        # waiter (no sentinel ever lands).
                        self._commit_first_token(slot, handle,
                                                 logits[len(prompt) - 1],
                                                 len(prompt))
                    except BaseException as e:
                        self._free_slot_pages(slot)
                        handle._finish(e)
                    continue
                W = self._batch_prefill_width
                npages_row = self.max_len // self.page_size
                tokens = np.zeros((W, bucket), np.int32)
                last_idx = np.zeros((W,), np.int32)
                page_rows = np.full((W, npages_row), self._dummy_page,
                                    np.int32)
                rows = []
                for r, (slot, seq_id, prompt, handle) in enumerate(chunk):
                    tokens[r, : len(prompt)] = prompt
                    last_idx[r] = len(prompt) - 1
                    row = np.asarray(self._alloc.table(seq_id,
                                                       self._np_pages))
                    rows.append(row)
                    npp = self._alloc.pages_needed(len(prompt))
                    page_rows[r, :npp] = row[:npp]
                # Sampling params padded to the FIXED width W: a partial
                # group must not compile its own (n, V) _sample variant.
                temps = np.zeros(W, np.float32)
                topks = np.zeros(W, np.int32)
                topps = np.ones(W, np.float32)
                for r, c in enumerate(chunk):
                    temps[r] = c[3].sampling.temperature
                    topks[r] = c[3].sampling.top_k
                    topps[r] = c[3].sampling.top_p
                try:
                    last_logits, kv_many = self._prefill_many(
                        self.params, jnp.asarray(tokens),
                        jnp.asarray(last_idx))
                    self._pools = self._write_prompt_pages_many(
                        self._pools, kv_many, jnp.asarray(page_rows))
                    # ONE sampling dispatch + host sync for the whole
                    # group (the sequential path pays one per request;
                    # greedy stays bit-equal — argmax ignores the rng
                    # mapping).
                    self._rng, srng = self._jax.random.split(self._rng)
                    toks = np.asarray(self._sample(
                        last_logits, temps, topks, topps, srng))
                except BaseException as e:
                    # Device-level failure sinks the whole dispatch: fail
                    # every member and return their pages.
                    for slot, seq_id, prompt, handle in chunk:
                        self._free_slot_pages(slot)
                        handle._finish(e)
                    continue
                # Host-only from here: no device call can strand waiters.
                for r, (slot, seq_id, prompt, handle) in enumerate(chunk):
                    self._tables[slot] = rows[r]
                    self._commit_token(slot, handle, int(toks[r]),
                                       len(prompt))

    def _init_paged_state(self):
        """(Re)build the page pool: allocator + dummy page + zeroed
        per-layer pools + tables. Shared by __init__ and the
        decode-failure recovery path so the two can never drift."""
        from ray_tpu.ops.paged_attention import PageAllocator

        jnp = self._jnp
        self._alloc = PageAllocator(self._num_pages, self.page_size)
        # Dummy page: inactive slots' garbage writes and table padding
        # land here, never in a page a live sequence owns.
        self._dummy_page = self._alloc.allocate("__dummy__", 1)[0]
        Hkv, Dh = self.cfg.n_kv_heads, self.cfg.head_dim
        self._pools = [
            (jnp.zeros((self._num_pages, Hkv, self.page_size, Dh),
                       self.cfg.dtype),
             jnp.zeros((self._num_pages, Hkv, self.page_size, Dh),
                       self.cfg.dtype))
            for _ in range(self.cfg.n_layers)]
        self._tables = np.full((self.max_batch, self._np_pages),
                               self._dummy_page, np.int32)

    def _prefill_into_pages(self, slot: int, seq_id: str,
                            prompt: np.ndarray):
        """Bucketed prefill through the dense program, scattering the
        prompt's KV into this sequence's pages; returns the logits."""
        jnp = self._jnp
        bucket = max(self._bucket(len(prompt)), self.page_size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(prompt)] = prompt
        logits, kv_one = self._prefill_one(self.params, jnp.asarray(padded))
        row = np.asarray(self._alloc.table(seq_id, self._np_pages))
        n_prompt_pages = self._alloc.pages_needed(len(prompt))
        prompt_pages = jnp.asarray(np.concatenate([
            row[:n_prompt_pages],
            np.full(self.max_len // self.page_size - n_prompt_pages,
                    self._dummy_page, np.int32)]))
        self._pools = self._write_prompt_pages(
            self._pools, kv_one, prompt_pages)
        self._tables[slot] = row
        return logits

    def _free_slot_pages(self, slot: int):
        st = self._slots[slot]
        if self.page_size and st.seq_id:
            self._alloc.free(st.seq_id)
            self._tables[slot, :] = self._dummy_page
            st.seq_id = ""

    def _advance_prefill(self, slot: int):
        """Write ONE chunk of a long prompt into the slot's cache; on the
        final chunk, sample the first token and switch to decoding."""
        jnp = self._jnp
        st = self._slots[slot]
        prompt = st.prefill_prompt
        C = self.prefill_chunk
        start = st.prefill_pos
        chunk = np.zeros((1, C), np.int32)
        n = min(C, len(prompt) - start)
        chunk[0, :n] = prompt[start: start + n]
        logits, kv_out = self._prefill_chunk(
            self.params, jnp.asarray(chunk), jnp.int32(start), self._kv,
            jnp.int32(slot))
        self._kv = [(k, v) for k, v in kv_out]
        st.prefill_pos = start + n
        if st.prefill_pos < len(prompt):
            return
        # Prompt complete: first token from the last REAL position's logits.
        self._rng, srng = self._jax.random.split(self._rng)
        sp = st.request.sampling
        tok = int(np.asarray(self._sample(
            logits[n - 1][None], np.float32([sp.temperature]),
            np.int32([sp.top_k]), np.float32([sp.top_p]), srng))[0])
        self._lens[slot] = len(prompt)
        self._pos[slot] = len(prompt)
        self._token[slot] = tok
        st.prefill_prompt = None
        self._ttft.append(time.monotonic() - st.request._submit_ts)
        self._emit(slot, tok)

    def _emit(self, slot: int, tok: int) -> bool:
        """Offer one token to the stream. False = the consumer's bounded
        queue is full: the caller must NOT commit the token — the slot
        parks (its decode cursor stays put) and the same token is
        re-produced next chunk once the consumer drains."""
        st = self._slots[slot]
        if not st.request._offer(tok):
            self._parked_events += 1
            return False
        st.generated += 1
        st.history.append(tok)
        sp = st.request.sampling
        if (sp.eos_token is not None and tok == sp.eos_token) or \
                st.generated >= sp.max_new_tokens:
            st.request._finish()
            st.request = None
            # Paged mode: the stream's pages return to the pool the
            # moment it completes — this is what lets a deferred request
            # admit on the next loop pass.
            self._free_slot_pages(slot)
        return True

    def _loop(self):
        jax, jnp = self._jax, self._jnp
        while not self._stop.is_set():
            # Drain quiesce: ack and idle at a tick boundary — every
            # admitted token is committed, so slot/KV state is a
            # consistent snapshot for the evacuation path.
            if self._quiesce.is_set():
                self._quiet.set()
                self._stop.wait(0.01)
                continue
            # Admit as many pending requests as there are free slots —
            # without stalling slots that are mid-decode. Paged mode also
            # gates on pool pages: a dry pool defers the request (FIFO)
            # until completions free pages. Paged admissions gathered in
            # one pass PREFILL TOGETHER (see _admit_paged_group) —
            # sequential slot prefills were the measured end-to-end
            # serving bottleneck at large batch.
            paged_cands: list = []
            picked: set = set()
            while any(i not in picked and s.request is None
                      for i, s in enumerate(self._slots)):
                from_deferred = bool(self.page_size and self._deferred)
                if from_deferred:
                    prompt, handle = self._deferred[0]
                else:
                    try:
                        prompt, handle = self._pending.get(
                            block=(self.num_active() == 0
                                   and not paged_cands), timeout=0.05)
                    except queue.Empty:
                        break
                if not self.page_size:
                    try:
                        self._admit(prompt, handle)
                        if from_deferred:
                            self._deferred.pop(0)
                    except Exception as e:  # surfacing beats a dead stream
                        if from_deferred:
                            self._deferred.pop(0)
                        handle._finish(e)
                    continue
                slot = next(i for i, s in enumerate(self._slots)
                            if s.request is None and i not in picked)
                try:
                    seq_id = self._reserve_paged(slot, prompt, handle)
                except MemoryError:
                    # Pool dry: keep FIFO order and stop admitting until
                    # a completion frees pages.
                    if not from_deferred:
                        self._deferred.append((prompt, handle))
                    break
                except Exception as e:
                    if from_deferred:
                        self._deferred.pop(0)
                    handle._finish(e)
                    continue
                if from_deferred:
                    self._deferred.pop(0)
                picked.add(slot)
                paged_cands.append((slot, seq_id, prompt, handle))
            if paged_cands:
                self._admit_paged_group(paged_cands)
            if self.num_active() == 0:
                continue
            # Advance ONE chunk of ONE prefilling slot per tick — long
            # prompts interleave with decoding instead of stalling it.
            prefilling = [i for i, s in enumerate(self._slots)
                          if s.request is not None
                          and s.prefill_prompt is not None]
            if prefilling:
                idx = prefilling[self._prefill_rr % len(prefilling)]
                self._prefill_rr += 1
                try:
                    self._advance_prefill(idx)
                except Exception as e:
                    st = self._slots[idx]
                    if st.request is not None:
                        st.request._finish(e)
                        st.request = None
                        st.prefill_prompt = None
            decoding = [s for s in self._slots
                        if s.request is not None and s.prefill_prompt is None]
            if not decoding:
                continue
            # Backpressure: if EVERY decoding stream's consumer queue is
            # full, a decode chunk would produce only parked tokens —
            # skip the dispatch and give the consumers time to drain.
            if all(s.request.backlog_full() for s in decoding):
                self._stop.wait(0.002)
                continue
            # One decode CHUNK for every slot (inactive slots compute
            # garbage on their stale state — discarded host-side; slots
            # finishing mid-chunk have their overshoot discarded too).
            try:
                self._rng, srng = jax.random.split(self._rng)
                if self.page_size:
                    toks, pools_out = self._decode_chunk_paged(
                        self.params, jnp.asarray(self._token),
                        jnp.asarray(self._pos), self._pools,
                        jnp.asarray(self._tables), jnp.asarray(self._lens),
                        jnp.asarray(self._temps), self._topks_arr(),
                        self._topps_arr(), srng)
                    self._pools = [(k, v) for k, v in pools_out]
                else:
                    toks, kv_out = self._decode_chunk_fn(
                        self.params, jnp.asarray(self._token),
                        jnp.asarray(self._pos), self._kv,
                        jnp.asarray(self._lens),
                        jnp.asarray(self._temps), self._topks_arr(),
                        self._topps_arr(), srng)
                    self._kv = [(k, v) for k, v in kv_out]
                toks = np.asarray(toks)  # (K, B)
            except Exception as e:
                # A decode failure (device OOM, donated-buffer misuse, ...)
                # must not strand waiters on a dead thread: fail loudly and
                # keep serving subsequent requests on fresh state.
                self._fail_all(e)
                if self.page_size:
                    self._init_paged_state()
                else:
                    proto = init_kv_caches(self.cfg, self.max_batch,
                                           self.max_len)
                    self._kv = [(k, v) for k, v, _l in proto]
                continue
            for i, st in enumerate(self._slots):
                if st.request is None or st.prefill_prompt is not None:
                    continue
                for kstep in range(toks.shape[0]):
                    tok = int(toks[kstep, i])
                    if not self._emit(i, tok):
                        # Consumer backlog full: park WITHOUT committing.
                        # Decode re-runs from the committed cursor next
                        # chunk — safe because decode writes KV at index
                        # lens before attending and masks kpos<=qpos, so
                        # the uncommitted steps' writes are garbage that
                        # is simply rewritten.
                        break
                    self._lens[i] += 1
                    self._pos[i] += 1
                    self._token[i] = tok
                    if st.request is None:  # eos/max_new hit mid-chunk
                        break


# ---------------------------------------------------------------------------
# Serve integration
# ---------------------------------------------------------------------------


class LLMServer:
    """Deployment callable hosting one LLMEngine per replica.

    Use with @serve.deployment:

        @serve.deployment
        class Chat(LLMServer):
            def __init__(self):
                cfg, params = load_my_model()
                super().__init__(cfg, params, max_batch=8, max_len=2048)

        serve.run(Chat.bind())
        handle.options(stream=True).remote({"prompt_tokens": [...],
                                            "max_new_tokens": 32})
    """

    def __init__(self, cfg: LlamaConfig, params, *, max_batch: int = 4,
                 max_len: int = 1024, decode_chunk: int = 8,
                 prefill_chunk: int = 0, page_size: int = 0,
                 kv_pool_tokens: int = 0, stream_buffer: int = 256):
        self.engine = LLMEngine(cfg, params, max_batch=max_batch,
                                max_len=max_len, decode_chunk=decode_chunk,
                                prefill_chunk=prefill_chunk,
                                page_size=page_size,
                                kv_pool_tokens=kv_pool_tokens,
                                stream_buffer=stream_buffer)

    def report_metrics(self) -> dict:
        return self.engine.report_metrics()

    def __call__(self, payload: dict):
        sp = SamplingParams(
            max_new_tokens=int(payload.get("max_new_tokens", 64)),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            eos_token=payload.get("eos_token"))
        handle = self.engine.submit(payload["prompt_tokens"], sp)
        for tok in handle:
            yield tok
