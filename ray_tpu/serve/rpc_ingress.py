"""Binary (msgpack-RPC) Serve ingress — the second protocol beside HTTP.

Parity: the reference proxy serves BOTH HTTP and gRPC on every node
(reference: serve/_private/proxy.py:13-38 — ProxyRequest duality). Here
the second, binary protocol is the repo's own length-prefixed msgpack
RPC framing (_private/rpc.py), so any in-repo client (or the C++
frontend's wire layer) can call deployments without HTTP/JSON overhead.

Wire protocol (all msgpack):
  request  "ServeCall"   {"deployment": str | None, "route": str | None,
                          "payload": value, "stream_id": str | None}
  reply                  {"ok": True, "result": value}            (unary)
                         {"ok": True, "stream": id}           (streaming)
                         {"ok": False, "error": str}
  notifies (streaming)   "ServeStreamChunk" {"stream": id, "chunk": v}
                         "ServeStreamEnd"   {"stream": id}
                         "ServeStreamError" {"stream": id, "error": str}
  request  "ServeStreamClose" {"stream": id}   — client stops early

Routing matches the HTTP proxy: explicit deployment name, else longest
matching route prefix from the controller's route table.
"""

from __future__ import annotations

import asyncio
import logging
import threading

from ray_tpu._private import rpc

logger = logging.getLogger(__name__)


class RpcIngress:
    """One binary ingress server (runs beside the HTTP proxy)."""

    def __init__(self):
        self._server = rpc.RpcServer({
            "ServeCall": self._call,
            "ServeStreamClose": self._stream_close,
            "Ping": lambda conn, p: {"ok": True},
        }, name="serve-rpc")
        self._loop: asyncio.AbstractEventLoop | None = None
        self._streams: dict[str, object] = {}  # id -> replica generator
        self.port: int | None = None

    def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        started = threading.Event()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def go():
                _, self.port = await self._server.start(host, port)
                started.set()

            self._loop.run_until_complete(go())
            self._loop.run_forever()

        threading.Thread(target=run, daemon=True,
                         name="serve-rpc-ingress").start()
        if not started.wait(10.0) or self.port is None:
            raise RuntimeError("serve rpc ingress failed to start")
        return self.port

    def _resolve(self, payload):
        from ray_tpu.serve import _ProxyHandler, get_deployment_handle

        name = payload.get("deployment")
        if not name:
            route = payload.get("route") or "/"
            best_len = -1
            for prefix, dep in _ProxyHandler._route_table().items():
                if (route == prefix
                        or route.startswith(prefix.rstrip("/") + "/")
                        or prefix == "/") and len(prefix) > best_len:
                    name, best_len = dep, len(prefix)
            if name is None:
                name = route.strip("/").split("/")[0]
        handle = _ProxyHandler.handles.get(name)
        if handle is None:
            handle = _ProxyHandler.handles[name] = get_deployment_handle(name)
        return handle

    async def _call(self, conn, payload):
        try:
            handle = await asyncio.to_thread(self._resolve, payload)
        except Exception as e:  # unknown deployment etc.
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        body = payload.get("payload")
        stream_id = payload.get("stream_id")
        if stream_id:
            loop = asyncio.get_running_loop()

            def pump():
                gen = None
                try:
                    gen = handle.options(stream=True).remote(body)
                    self._streams[stream_id] = gen
                    for chunk in gen:
                        if stream_id not in self._streams or conn.closed:
                            gen.cancel()
                            return
                        asyncio.run_coroutine_threadsafe(
                            conn.notify("ServeStreamChunk",
                                        {"stream": stream_id,
                                         "chunk": chunk}), loop).result(30)
                    asyncio.run_coroutine_threadsafe(
                        conn.notify("ServeStreamEnd", {"stream": stream_id}),
                        loop).result(30)
                except Exception as e:  # noqa: BLE001
                    try:
                        asyncio.run_coroutine_threadsafe(
                            conn.notify("ServeStreamError",
                                        {"stream": stream_id,
                                         "error": f"{e}"}), loop).result(30)
                    except Exception:
                        pass
                    if gen is not None:
                        try:
                            gen.cancel()
                        except Exception:
                            pass
                finally:
                    self._streams.pop(stream_id, None)

            threading.Thread(target=pump, daemon=True,
                             name=f"serve-rpc-stream-{stream_id[:8]}").start()
            return {"ok": True, "stream": stream_id}
        try:
            result = await asyncio.to_thread(
                lambda: handle.remote(body).result(timeout=60))
            return {"ok": True, "result": result}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    async def _stream_close(self, conn, payload):
        gen = self._streams.pop(payload.get("stream"), None)
        if gen is not None:
            try:
                gen.cancel()
            except Exception:
                pass
        return {"ok": True}

    def stop(self):
        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(self._server.stop(), self._loop)
            self._loop.call_soon_threadsafe(self._loop.stop)


class RpcIngressClient:
    """Minimal client for the binary ingress (used by tests and as the
    reference pattern for non-HTTP callers)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        import queue as _queue
        import uuid as _uuid

        self._uuid = _uuid
        self._queue_mod = _queue
        self._streams: dict[str, _queue.Queue] = {}
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        daemon=True, name="serve-rpc-client")
        self._thread.start()
        self._conn = asyncio.run_coroutine_threadsafe(
            rpc.dial(host, port, handlers={
                "ServeStreamChunk": self._on_stream,
                "ServeStreamEnd": self._on_stream,
                "ServeStreamError": self._on_stream,
            }, name="serve-rpc-client", timeout=timeout),
            self._loop).result(timeout + 5)

    async def _on_stream(self, conn, payload):
        q = self._streams.get(payload["stream"])
        if q is None:
            return
        if "chunk" in payload:
            q.put(("chunk", payload["chunk"]))
        elif "error" in payload:
            q.put(("error", payload["error"]))
        else:
            q.put(("end", None))

    def _rpc(self, method, payload, timeout=70.0):
        return asyncio.run_coroutine_threadsafe(
            self._conn.call(method, payload, timeout=timeout),
            self._loop).result(timeout + 5)

    def call(self, payload, *, deployment: str | None = None,
             route: str | None = None, timeout: float = 70.0):
        resp = self._rpc("ServeCall", {"deployment": deployment,
                                       "route": route, "payload": payload},
                         timeout=timeout)
        if not resp["ok"]:
            raise RuntimeError(resp["error"])
        return resp["result"]

    def stream(self, payload, *, deployment: str | None = None,
               route: str | None = None):
        """Yield chunks from a streaming deployment call."""
        stream_id = self._uuid.uuid4().hex[:16]
        q = self._queue_mod.Queue()
        self._streams[stream_id] = q
        resp = self._rpc("ServeCall", {"deployment": deployment,
                                       "route": route, "payload": payload,
                                       "stream_id": stream_id})
        if not resp.get("ok"):
            self._streams.pop(stream_id, None)
            raise RuntimeError(resp.get("error", "stream start failed"))
        try:
            while True:
                kind, val = q.get(timeout=120)
                if kind == "chunk":
                    yield val
                elif kind == "end":
                    return
                else:
                    raise RuntimeError(val)
        finally:
            self._streams.pop(stream_id, None)

    def close_stream(self, stream_id: str):
        try:
            self._rpc("ServeStreamClose", {"stream": stream_id}, timeout=10)
        except Exception:
            pass

    def close(self):
        try:
            asyncio.run_coroutine_threadsafe(
                self._conn.close(), self._loop).result(5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(5)
