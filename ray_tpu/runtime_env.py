"""Per-task/actor runtime environments.

Parity: reference python/ray/runtime_env/runtime_env.py +
_private/runtime_env/ plugins (design doc: python/ray/runtime_env/
ARCHITECTURE.md) — env_vars, working_dir, py_modules, and a plugin hook
API. The reference materializes envs through a per-node RuntimeEnvAgent
with ref-counted caching; here nodes share a filesystem (fake-multinode
model, SURVEY.md §4), so materialization is in-process at task execution:
env vars are swapped around the task, working_dir/py_modules go onto
sys.path, and plugins get a setup callback in the worker.

Supported fields:
  env_vars: dict[str, str]      — set for the duration of the task; for
                                  actors they persist (dedicated process).
  working_dir: str              — chdir + sys.path for the task. A local
                                  DIRECTORY is packed + uploaded to the
                                  GCS KV at submission (gcskv:// URI,
                                  reference working_dir upload); zip
                                  URIs are extracted node-side.
  py_modules: list[str]         — directories prepended to sys.path
                                  (same packing/URI handling).
  pip: list[str]                — requirements installed into an
                                  isolated, node-cached site-packages dir
                                  (reference: _private/runtime_env/pip.py)
                                  prepended to sys.path for the task.
  config: dict                  — opaque; passed to plugins.
  <plugin name>: Any            — handled by a registered plugin.

Provisioning (pip envs, package extraction) runs in the RAYLET's
RuntimeEnvManager — cached per node, ref-counted per job, GC'd when the
GCS publishes the job-finished event (_private/runtime_env_manager.py).
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Callable

_KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "pip", "config"}

# name -> setup(value, env_dict) callback, run in the executing worker.
_PLUGINS: dict[str, Callable[[Any, dict], None]] = {}


def register_plugin(name: str, setup: Callable[[Any, dict], None]) -> None:
    """Register a runtime_env plugin (parity: reference RuntimeEnvPlugin
    classes registered via RAY_RUNTIME_ENV_PLUGINS)."""
    _PLUGINS[name] = setup


def unregister_plugin(name: str) -> None:
    _PLUGINS.pop(name, None)


class RuntimeEnv(dict):
    """Validated runtime environment; behaves as a plain dict on the wire."""

    def __init__(self, *, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 pip: list | None = None,
                 config: dict | None = None, **plugin_fields):
        super().__init__()
        if pip is not None:
            if isinstance(pip, str) or \
                    not all(isinstance(r, str) for r in pip):
                raise TypeError(
                    "pip must be a LIST of requirement strings "
                    "(a bare string would be split per-character)")
            self["pip"] = list(pip)
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            if not isinstance(working_dir, str):
                raise TypeError("working_dir must be a path string")
            self["working_dir"] = working_dir
        if py_modules is not None:
            if not isinstance(py_modules, (list, tuple)):
                raise TypeError("py_modules must be a list of paths")
            self["py_modules"] = list(py_modules)
        if config is not None:
            self["config"] = dict(config)
        for k, v in plugin_fields.items():
            if k not in _PLUGINS:
                raise ValueError(
                    f"unknown runtime_env field {k!r} (no plugin registered)")
            self[k] = v

    @staticmethod
    def merge(parent: dict | None, child: dict | None) -> dict | None:
        """Child overrides parent per-field; env_vars merge key-wise
        (reference semantics for job → task inheritance)."""
        if not parent:
            return dict(child) if child else None
        if not child:
            return dict(parent)
        out = dict(parent)
        for k, v in child.items():
            if k == "env_vars" and "env_vars" in out:
                merged = dict(out["env_vars"])
                merged.update(v)
                out["env_vars"] = merged
            else:
                out[k] = v
        return out


# abspath -> uploaded gcskv:// URI. One fingerprint+upload per dir per
# driver process (reference semantics: working_dir is uploaded once per
# job; later edits to the dir are not re-uploaded mid-job) — also keeps
# the per-submission hot path free of directory walks.
_pack_cache: dict = {}


def _is_package_uri(s: str) -> bool:
    return s.startswith(("gcskv://", "file://")) or s.endswith(".zip")


def _upload_local_dir(path: str) -> str:
    """Pack a local dir and store it in the GCS KV; returns gcskv:// URI.
    Content-addressed: identical trees dedupe server-side."""
    from ray_tpu._private.api_internal import core_worker_or_none
    from ray_tpu._private.runtime_env_manager import (
        package_local_dir, package_uri_for)

    path = os.path.abspath(os.path.expanduser(path))
    cw = core_worker_or_none()
    if cw is None:
        return path  # no cluster yet: leave as a direct path
    uri = _pack_cache.get(path)
    if uri is not None:
        return uri
    data = package_local_dir(path)
    uri = package_uri_for(data)
    kv_key = uri[len("gcskv://pkg/"):]
    cw._run(cw.gcs.call("KVPut", {"ns": "pkg", "key": kv_key.encode(),
                                  "value": data, "overwrite": False}))
    _pack_cache[path] = uri
    return uri


def prepare_for_wire(env: dict | None) -> dict | None:
    """Submission-side packaging: local working_dir / py_modules
    directories become uploaded gcskv:// packages so any node can
    materialize them (reference: working_dir/py_modules upload to GCS in
    _private/runtime_env/packaging.py)."""
    if not env:
        return env
    wd = env.get("working_dir")
    mods = env.get("py_modules")
    if not wd and not mods:
        return env
    out = dict(env)
    try:
        if wd and not _is_package_uri(wd) and os.path.isdir(wd):
            out["working_dir"] = _upload_local_dir(wd)
        if mods:
            out["py_modules"] = [
                _upload_local_dir(m)
                if not _is_package_uri(m) and os.path.isdir(m) else m
                for m in mods]
    except ValueError:
        # Oversized package: fall back to the direct path (shared-FS
        # deployments still work; remote nodes would fail at setup).
        return env
    return out


def _resolve_provisioned(env: dict, job_id: str = "") -> dict:
    """Worker-side: ask this node's raylet to materialize pip envs and
    package URIs (cached + ref-counted there under the SUBMITTING job's
    id, so job-finish GC sees real references); swap local paths in."""
    needs = env.get("pip") or _is_package_uri(env.get("working_dir") or "") \
        or any(_is_package_uri(m) for m in env.get("py_modules") or [])
    if not needs:
        return env
    from ray_tpu._private.api_internal import core_worker_or_none

    cw = core_worker_or_none()
    if cw is None or cw.raylet is None:
        raise RuntimeEnvSetupError(
            "provisioned runtime_env fields (pip / package URIs) need a "
            "running cluster")
    ctx = cw.ensure_runtime_env(env, job_id)
    out = dict(env)
    if ctx.get("working_dir"):
        out["working_dir"] = ctx["working_dir"]
    if ctx.get("py_modules"):
        out["py_modules"] = ctx["py_modules"]
    if ctx.get("pip_dir"):
        # Isolated site-packages: prepend like a py_module.
        out["py_modules"] = [ctx["pip_dir"]] + list(out.get("py_modules") or [])
        out.pop("pip", None)
    return out


@contextlib.contextmanager
def runtime_env_context(env: dict | None, *, persistent: bool = False,
                        job_id: str = ""):
    """Materialize `env` in this process for the duration of the block.

    persistent=True (actor creation) applies without restoring — the worker
    process is dedicated to the actor, matching the reference's
    runtime-env-keyed worker processes (worker_pool.cc runtime env hash).
    job_id attributes provisioning references for job-finish GC.
    """
    if not env:
        yield
        return
    env = _resolve_provisioned(env, job_id)

    # Validate BEFORE mutating any process state: a setup error must leave
    # the pooled worker exactly as it was (otherwise a failed task leaks
    # env vars / cwd / sys.path entries into every later task).
    wd = env.get("working_dir")
    if wd:
        wd = os.path.abspath(os.path.expanduser(wd))
        if not os.path.isdir(wd):
            raise RuntimeEnvSetupError(f"working_dir {wd!r} does not exist")
    py_modules = []
    for p in env.get("py_modules") or []:
        p = os.path.abspath(os.path.expanduser(p))
        if not os.path.exists(p):
            raise RuntimeEnvSetupError(f"py_module {p!r} does not exist")
        py_modules.append(p)

    saved_env: dict[str, str | None] = {}
    saved_cwd = None
    added_paths: list[str] = []
    applied = False
    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        if wd:
            saved_cwd = os.getcwd()
            os.chdir(wd)
            if wd not in sys.path:
                sys.path.insert(0, wd)
                added_paths.append(wd)
        for p in py_modules:
            if p not in sys.path:
                sys.path.insert(0, p)
                added_paths.append(p)
        for name, setup in _PLUGINS.items():
            if name in env:
                setup(env[name], env)
        applied = True
        yield
    finally:
        # Restore on any exit except a fully-applied persistent (actor) env.
        if not (persistent and applied):
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            if saved_cwd is not None:
                os.chdir(saved_cwd)
            for p in added_paths:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass


# Job-level default, inherited by tasks/actors without their own
# runtime_env (set by ray_tpu.init(runtime_env=...)).
_job_runtime_env: dict | None = None


def set_job_runtime_env(env: dict | None) -> None:
    global _job_runtime_env
    _job_runtime_env = dict(env) if env else None


def get_job_runtime_env() -> dict | None:
    return _job_runtime_env


from ray_tpu.exceptions import RuntimeEnvSetupError  # noqa: E402  (cycle-safe)

__all__ = ["RuntimeEnv", "register_plugin", "unregister_plugin",
           "runtime_env_context", "set_job_runtime_env",
           "get_job_runtime_env"]
