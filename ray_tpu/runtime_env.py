"""Per-task/actor runtime environments.

Parity: reference python/ray/runtime_env/runtime_env.py +
_private/runtime_env/ plugins (design doc: python/ray/runtime_env/
ARCHITECTURE.md) — env_vars, working_dir, py_modules, and a plugin hook
API. The reference materializes envs through a per-node RuntimeEnvAgent
with ref-counted caching; here nodes share a filesystem (fake-multinode
model, SURVEY.md §4), so materialization is in-process at task execution:
env vars are swapped around the task, working_dir/py_modules go onto
sys.path, and plugins get a setup callback in the worker.

Supported fields:
  env_vars: dict[str, str]      — set for the duration of the task; for
                                  actors they persist (dedicated process).
  working_dir: str              — chdir + sys.path for the task.
  py_modules: list[str]         — directories prepended to sys.path.
  config: dict                  — opaque; passed to plugins.
  <plugin name>: Any            — handled by a registered plugin.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Callable

_KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "config"}

# name -> setup(value, env_dict) callback, run in the executing worker.
_PLUGINS: dict[str, Callable[[Any, dict], None]] = {}


def register_plugin(name: str, setup: Callable[[Any, dict], None]) -> None:
    """Register a runtime_env plugin (parity: reference RuntimeEnvPlugin
    classes registered via RAY_RUNTIME_ENV_PLUGINS)."""
    _PLUGINS[name] = setup


def unregister_plugin(name: str) -> None:
    _PLUGINS.pop(name, None)


class RuntimeEnv(dict):
    """Validated runtime environment; behaves as a plain dict on the wire."""

    def __init__(self, *, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 config: dict | None = None, **plugin_fields):
        super().__init__()
        if env_vars is not None:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            if not isinstance(working_dir, str):
                raise TypeError("working_dir must be a path string")
            self["working_dir"] = working_dir
        if py_modules is not None:
            if not isinstance(py_modules, (list, tuple)):
                raise TypeError("py_modules must be a list of paths")
            self["py_modules"] = list(py_modules)
        if config is not None:
            self["config"] = dict(config)
        for k, v in plugin_fields.items():
            if k not in _PLUGINS:
                raise ValueError(
                    f"unknown runtime_env field {k!r} (no plugin registered)")
            self[k] = v

    @staticmethod
    def merge(parent: dict | None, child: dict | None) -> dict | None:
        """Child overrides parent per-field; env_vars merge key-wise
        (reference semantics for job → task inheritance)."""
        if not parent:
            return dict(child) if child else None
        if not child:
            return dict(parent)
        out = dict(parent)
        for k, v in child.items():
            if k == "env_vars" and "env_vars" in out:
                merged = dict(out["env_vars"])
                merged.update(v)
                out["env_vars"] = merged
            else:
                out[k] = v
        return out


@contextlib.contextmanager
def runtime_env_context(env: dict | None, *, persistent: bool = False):
    """Materialize `env` in this process for the duration of the block.

    persistent=True (actor creation) applies without restoring — the worker
    process is dedicated to the actor, matching the reference's
    runtime-env-keyed worker processes (worker_pool.cc runtime env hash).
    """
    if not env:
        yield
        return

    # Validate BEFORE mutating any process state: a setup error must leave
    # the pooled worker exactly as it was (otherwise a failed task leaks
    # env vars / cwd / sys.path entries into every later task).
    wd = env.get("working_dir")
    if wd:
        wd = os.path.abspath(os.path.expanduser(wd))
        if not os.path.isdir(wd):
            raise RuntimeEnvSetupError(f"working_dir {wd!r} does not exist")
    py_modules = []
    for p in env.get("py_modules") or []:
        p = os.path.abspath(os.path.expanduser(p))
        if not os.path.exists(p):
            raise RuntimeEnvSetupError(f"py_module {p!r} does not exist")
        py_modules.append(p)

    saved_env: dict[str, str | None] = {}
    saved_cwd = None
    added_paths: list[str] = []
    applied = False
    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        if wd:
            saved_cwd = os.getcwd()
            os.chdir(wd)
            if wd not in sys.path:
                sys.path.insert(0, wd)
                added_paths.append(wd)
        for p in py_modules:
            if p not in sys.path:
                sys.path.insert(0, p)
                added_paths.append(p)
        for name, setup in _PLUGINS.items():
            if name in env:
                setup(env[name], env)
        applied = True
        yield
    finally:
        # Restore on any exit except a fully-applied persistent (actor) env.
        if not (persistent and applied):
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            if saved_cwd is not None:
                os.chdir(saved_cwd)
            for p in added_paths:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass


# Job-level default, inherited by tasks/actors without their own
# runtime_env (set by ray_tpu.init(runtime_env=...)).
_job_runtime_env: dict | None = None


def set_job_runtime_env(env: dict | None) -> None:
    global _job_runtime_env
    _job_runtime_env = dict(env) if env else None


def get_job_runtime_env() -> dict | None:
    return _job_runtime_env


from ray_tpu.exceptions import RuntimeEnvSetupError  # noqa: E402  (cycle-safe)

__all__ = ["RuntimeEnv", "register_plugin", "unregister_plugin",
           "runtime_env_context", "set_job_runtime_env",
           "get_job_runtime_env"]
