/* ray_tpu dashboard SPA (parity: reference dashboard/client/src React
   app — node/actor/job/task/serve/log/metrics/profiling views). A
   dependency-free hash router over the head server's /api/* JSON.

   Conventions: every list view gets a client-side text filter and
   click-to-sort headers; entity ids link to detail routes; state-ish
   columns render as colored pills. Data auto-refreshes every 3 s
   (toggle in the sidebar) for the current view only. */
"use strict";

const $ = (id) => document.getElementById(id);

function esc(v) {
  if (v === null || v === undefined) return "";
  return String(v).replace(/&/g, "&amp;").replace(/</g, "&lt;")
    .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}

async function getJSON(url) {
  const r = await fetch(url);
  const data = await r.json();
  if (!r.ok) throw new Error(data.error || r.status + " " + url);
  return data;
}

async function getText(url) {
  const r = await fetch(url);
  return await r.text();
}

function fmtBytes(n) {
  if (n === null || n === undefined || isNaN(n)) return "";
  const u = ["B", "KiB", "MiB", "GiB", "TiB"];
  let i = 0;
  while (n >= 1024 && i < u.length - 1) { n /= 1024; i++; }
  return n.toFixed(i ? 1 : 0) + " " + u[i];
}

function fmtDur(s) {
  if (s === null || s === undefined) return "";
  s = Math.floor(s);
  const h = Math.floor(s / 3600), m = Math.floor((s % 3600) / 60);
  return (h ? h + "h " : "") + (m ? m + "m " : "") + (s % 60) + "s";
}

function pill(v) {
  return `<span class="pill ${esc(v)}">${esc(v)}</span>`;
}

// Resource accounting is float-based; round for display so fractional
// CPUs don't render as 0.30000000000000004.
function fmtNum(v) { return Math.round(v * 100) / 100; }

function bar(used, total) {
  const frac = total > 0 ? used / total : 0;
  const hot = frac > 0.85 ? " hot" : "";
  return `<span class="bar-outer"><span class="bar-inner${hot}" ` +
    `style="width:${Math.round(frac * 120)}px"></span></span> ` +
    `${fmtNum(used)}/${fmtNum(total)}`;
}

function card(k, v, cls) {
  return `<div class="card"><div class="k">${esc(k)}</div>` +
    `<div class="v ${cls || ""}">${v}</div></div>`;
}

// ---- sortable/filterable table ------------------------------------------
// Table state (sort key/dir, filter text) persists per route across the
// 3 s refreshes so the view doesn't snap back while you read it.
const tableState = {};

function renderTable(rows, opts = {}) {
  const id = opts.id || location.hash;
  const st = tableState[id] || (tableState[id] = { sort: null, dir: 1, q: "" });
  if (!Array.isArray(rows)) rows = rows ? [rows] : [];
  let cols = opts.cols;
  if (!cols && rows.length) cols = Object.keys(rows[0]);
  if (!cols) cols = [];
  let filtered = rows;
  if (st.q) {
    const q = st.q.toLowerCase();
    filtered = rows.filter((r) =>
      cols.some((c) => String(r[c] ?? "").toLowerCase().includes(q)));
  }
  if (st.sort) {
    filtered = filtered.slice().sort((a, b) => {
      const x = a[st.sort], y = b[st.sort];
      if (typeof x === "number" && typeof y === "number")
        return (x - y) * st.dir;
      return String(x ?? "").localeCompare(String(y ?? "")) * st.dir;
    });
  }
  const ths = cols.map((c) =>
    `<th data-col="${esc(c)}" data-table="${esc(id)}">${esc(c)}` +
    (st.sort === c ? ` <span class="arrow">${st.dir > 0 ? "▲" : "▼"}</span>`
                   : "") + `</th>`).join("");
  const fmt = opts.fmt || {};
  const trs = filtered.map((r) => "<tr>" + cols.map((c) => {
    let v = r[c];
    v = fmt[c] ? fmt[c](v, r) : esc(typeof v === "object" && v !== null
                                    ? JSON.stringify(v) : v);
    return `<td title="${esc(typeof r[c] === "object" ? JSON.stringify(r[c])
                                                      : r[c])}">${v}</td>`;
  }).join("") + "</tr>").join("");
  const filterBox = opts.noFilter ? "" :
    `<input class="filter" placeholder="filter…" data-table="${esc(id)}" ` +
    `value="${esc(st.q)}">`;
  return filterBox +
    `<table><thead><tr>${ths}</tr></thead><tbody>` +
    (trs || `<tr><td colspan="${cols.length || 1}"><i>none</i></td></tr>`) +
    `</tbody></table>`;
}

document.addEventListener("click", (e) => {
  const th = e.target.closest("th[data-col]");
  if (!th) return;
  const st = tableState[th.dataset.table] ||
    (tableState[th.dataset.table] = { sort: null, dir: 1, q: "" });
  if (st.sort === th.dataset.col) st.dir = -st.dir;
  else { st.sort = th.dataset.col; st.dir = 1; }
  render();
});

document.addEventListener("input", (e) => {
  const inp = e.target.closest("input.filter");
  if (!inp) return;
  const st = tableState[inp.dataset.table] ||
    (tableState[inp.dataset.table] = { sort: null, dir: 1, q: "" });
  st.q = inp.value;
  // Re-render but keep focus + caret in the filter box.
  const pos = inp.selectionStart;
  render().then(() => {
    const again = document.querySelector(
      `input.filter[data-table="${CSS.escape(inp.dataset.table)}"]`);
    if (again) { again.focus(); again.setSelectionRange(pos, pos); }
  });
});

// ---- views ---------------------------------------------------------------

const idLink = (route) => (v) =>
  `<a href="#/${route}/${esc(v)}">${esc(String(v).slice(0, 10))}</a>`;

const VIEWS = {
  async overview() {
    const [cs, ver, tasks, actors, objects] = await Promise.all([
      getJSON("/api/cluster_status"), getJSON("/api/version"),
      getJSON("/api/summary"), getJSON("/api/summary/actors"),
      getJSON("/api/summary/objects")]);
    const alive = cs.nodes.filter((n) => n.alive);
    let cpuT = 0, cpuA = 0;
    for (const n of alive) {
      cpuT += n.total_resources.CPU || 0;
      cpuA += n.available_resources.CPU || 0;
    }
    let h = "<h1>Cluster overview</h1><div class='cards'>" +
      card("version", esc(ver.version)) +
      card("nodes alive", `${alive.length}/${cs.nodes.length}`,
           alive.length === cs.nodes.length ? "ok" : "bad") +
      card("CPUs in use", `${fmtNum(cpuT - cpuA)}/${fmtNum(cpuT)}`) +
      card("actors", cs.actors) +
      card("placement groups", cs.placement_groups) +
      card("pending demand", cs.pending_demand.length,
           cs.pending_demand.length ? "bad" : "ok") +
      card("uptime", fmtDur(cs.uptime_s)) + "</div>";
    h += "<h2>Per-node utilization</h2>" + renderTable(alive.map((n) => ({
      node_id: n.node_id, host: n.host, head: n.is_head,
      cpu: (n.total_resources.CPU || 0) - (n.available_resources.CPU || 0),
      cpu_total: n.total_resources.CPU || 0,
    })), {
      id: "ov-nodes", noFilter: true,
      cols: ["node_id", "host", "head", "cpu"],
      fmt: { node_id: idLink("nodes"),
             cpu: (v, r) => bar(v, r.cpu_total) },
    });
    const stateRows = Object.entries(tasks.by_state || {})
      .map(([k, v]) => ({ state: k, tasks: v }));
    for (const [k, v] of Object.entries(actors.by_state || {})) {
      stateRows.push({ state: k, actors: v });
    }
    h += "<h2>Task / actor states</h2>" +
      renderTable(stateRows, { id: "ov-states", noFilter: true,
        cols: ["state", "tasks", "actors"], fmt: { state: (v) => pill(v) } });
    h += "<h2>Objects (driver-owned)</h2><div class='cards'>" +
      card("count", Object.values(objects.by_state || {})
        .reduce((a, b) => a + b, 0)) +
      card("bytes", fmtBytes(objects.total_bytes)) + "</div>";
    return h;
  },

  async nodes(id) {
    if (id) return VIEWS._nodeDetail(id);
    const [nodes, stats] = await Promise.all([
      getJSON("/api/nodes"), getJSON("/api/node_stats")]);
    const byId = Object.fromEntries(stats.map((s) => [s.node_id, s]));
    const rows = nodes.map((n) => {
      const s = byId[n.node_id] || {};
      const ds = n.drain_stats || {};
      return {
        // Lifecycle ladder from the GCS node table: ALIVE / SUSPECT /
        // DRAINING / DRAINED / DEAD (a DRAINED death is a clean
        // removal; SUSPECT = connection lost, inside the grace window).
        node_id: n.node_id, host: n.host,
        state: n.state || (n.alive ? "ALIVE" : "DEAD"),
        flaps: n.suspect_recoveries || 0,
        head: n.is_head, cpu_used:
          (n.total_resources.CPU || 0) - (n.available_resources.CPU || 0),
        cpu_total: n.total_resources.CPU || 0,
        workers: s.num_workers, pending: s.pending_leases,
        store_bytes: (s.store || {}).bytes_in_use,
        spilled: s.spilled_bytes,
        drain: n.drain_reason
          ? `${n.drain_reason}: ${ds.evacuated_objects || 0} obj/` +
            `${ds.evacuated_device_objects || 0} dev/` +
            `${ds.respilled_leases || 0} leases in ` +
            `${ds.duration_s != null ? ds.duration_s + "s" : "…"}`
          : "",
      };
    });
    return "<h1>Nodes</h1>" + renderTable(rows, {
      fmt: { node_id: idLink("nodes"), state: (v) => pill(v),
             cpu_used: (v, r) => bar(v, r.cpu_total),
             store_bytes: (v) => fmtBytes(v), spilled: (v) => fmtBytes(v) },
    });
  },

  async _nodeDetail(id) {
    // Every fetch here is narrowed to this node — an open detail tab
    // refreshing every 3 s must not fan out to the whole cluster.
    const nid = encodeURIComponent(id);
    const [nodes, stats, workers, logs] = await Promise.all([
      getJSON("/api/nodes"), getJSON("/api/node_stats?node=" + nid),
      getJSON("/api/worker_stats?node=" + nid),
      getJSON("/api/logs?node=" + nid)]);
    const node = nodes.find((n) => n.node_id === id);
    if (!node) return `<h1>Node ${esc(id)}</h1>not found`;
    const stat = stats.find((s) => s.node_id === id) || {};
    let h = `<h1>Node ${esc(id.slice(0, 12))}…</h1>` +
      `<pre class="json">${esc(JSON.stringify({ ...node, ...stat },
                                              null, 2))}</pre>`;
    const rows = workers.filter((w) => w.node_id === id);
    if (rows.length) {
      h += "<h2>Workers</h2>" + renderTable(rows, {
        id: "node-workers",
        cols: ["worker_id", "pid", "actor", "leased", "blocked", "cpu_s",
               "rss_mb"],
      });
    }
    h += "<h2>Log files</h2>" + renderTable(logs, {
      id: "node-logs", cols: ["file", "size", "view"],
      fmt: { size: (v) => fmtBytes(v), view: (v, r) =>
        `<a href="#/logs/${esc(id)}/${encodeURIComponent(r.file)}">tail</a>` },
    });
    return h;
  },

  async actors(id) {
    const actors = await getJSON("/api/actors");
    if (id) {
      const a = actors.find((x) => x.actor_id === id);
      return `<h1>Actor ${esc(id.slice(0, 12))}…</h1>` +
        (a ? `<pre class="json">${esc(JSON.stringify(a, null, 2))}</pre>`
           : "not found");
    }
    return "<h1>Actors</h1>" + renderTable(actors, {
      cols: ["actor_id", "class_name", "name", "namespace", "state",
             "node_id", "restarts", "job_id"],
      fmt: { actor_id: idLink("actors"), state: (v) => pill(v),
             node_id: idLink("nodes") },
    });
  },

  async tasks() {
    const [summary, tasks] = await Promise.all([
      getJSON("/api/summary"), getJSON("/api/tasks")]);
    let h = "<h1>Tasks</h1><div class='cards'>";
    for (const [k, v] of Object.entries(summary.by_state || {}))
      h += card(k, v, k === "FAILED" ? "bad" : "");
    h += "</div><h2>By function</h2>" + renderTable(
      Object.entries(summary.by_name || {}).map(([k, v]) =>
        ({ name: k, count: v })), { id: "task-names", noFilter: true });
    h += "<h2>Recent task events</h2>" + renderTable(
      tasks.slice().reverse(), {
        cols: ["task_id", "name", "state", "node_id", "worker_id", "job_id"],
        fmt: { state: (v) => pill(v), node_id: idLink("nodes"),
               task_id: (v) => esc(String(v).slice(0, 12)),
               worker_id: (v) => esc(String(v).slice(0, 10)) },
      });
    return h;
  },

  async objects() {
    const [objects, summary] = await Promise.all([
      getJSON("/api/objects"), getJSON("/api/summary/objects")]);
    let h = "<h1>Objects (owned by the dashboard's driver)</h1>" +
      "<div class='cards'>" +
      card("total bytes", fmtBytes(summary.total_bytes));
    for (const [k, v] of Object.entries(summary.by_state || {}))
      h += card(k, v);
    h += "</div>" + renderTable(objects, {
      fmt: { size: (v) => fmtBytes(v) } });
    return h;
  },

  async pgs() {
    return "<h1>Placement groups</h1>" + renderTable(
      await getJSON("/api/placement_groups"),
      { fmt: { state: (v) => pill(v) } });
  },

  async jobs() {
    const [jobs, sjobs] = await Promise.all([
      getJSON("/api/jobs"), getJSON("/api/submission_jobs")]);
    let h = "<h1>Driver jobs</h1>" + renderTable(jobs.map((j) => ({
      job_id: j.job_id, status: j.status, entrypoint: j.entrypoint,
      runtime: fmtDur((j.end_time || Date.now() / 1000) - j.start_time),
    })), { id: "jobs", fmt: { status: (v) => pill(v) } });
    h += "<h2>Submitted jobs</h2>" + renderTable(sjobs, {
      id: "sjobs",
      cols: ["submission_id", "status", "entrypoint", "message", "logs"],
      fmt: { status: (v) => pill(v),
             logs: (v, r) =>
               `<a href="#/jobs/logs/${esc(r.submission_id)}">logs</a>` },
    });
    return h;
  },

  async "jobs/logs"(sid) {
    const text = await getText(
      "/api/submission_jobs/logs?id=" + encodeURIComponent(sid));
    return `<h1>Job logs: ${esc(sid)}</h1>` +
      `<pre class="logview">${esc(text) || "(empty)"}</pre>`;
  },

  async serve() {
    const data = await getJSON("/api/serve");
    const rows = Object.entries(data).map(([name, d]) =>
      typeof d === "object" ? { deployment: name, ...d } : { deployment: name,
        info: d });
    return "<h1>Serve deployments</h1>" + renderTable(rows,
      { fmt: { status: (v) => pill(v) } });
  },

  async workflows() {
    return "<h1>Workflows</h1>" + renderTable(
      await getJSON("/api/workflows"), { fmt: { status: (v) => pill(v) } });
  },

  async logs(node, name) {
    if (node && name) {
      // route() already URI-decoded the args; re-encode for the query
      // string but never decode again (a literal '%' in a filename
      // would throw).
      const text = await getText(`/logs/view?node=${esc(node)}&name=` +
                                 encodeURIComponent(name));
      return `<h1>${esc(name)}</h1>` +
        `<div class="note">node ${esc(node.slice(0, 12))}… · last 64 KiB · ` +
        `auto-refreshes</div><pre class="logview">${esc(text)}</pre>`;
    }
    const logs = await getJSON("/api/logs");
    return "<h1>Logs</h1>" + renderTable(logs, {
      cols: ["node", "file", "size", "view"],
      fmt: {
        size: (v) => fmtBytes(v),
        view: (v, r) => `<a href="#/logs/${esc(r.node_id)}/` +
          `${encodeURIComponent(r.file)}">tail</a>`,
      },
    });
  },

  async events() {
    const events = await getJSON("/api/events");
    return "<h1>Cluster events</h1>" + renderTable(
      events.slice().reverse().map((e) => ({
        time: new Date(e.ts * 1000).toISOString().slice(11, 19),
        severity: e.severity, source: e.source, message: e.message,
        fields: e.fields,
      })), { fmt: { severity: (v) => pill(v) } });
  },

  async metrics() {
    const [hist, text] = await Promise.all([
      getJSON("/api/metrics/history"), getText("/metrics")]);
    const samples = hist.samples || [];
    const series = (pick) => samples.map((s) => ({ t: s.ts, v: pick(s) }));
    const sumNodes = (s, k) =>
      Object.values(s.nodes || {}).reduce((a, n) => a + (n[k] || 0), 0);
    let charts = "";
    if (samples.length >= 2) {
      charts = "<div class='chart-grid'>" +
        lineChart("CPU in use", "cores",
                  series((s) => sumNodes(s, "cpu_used"))) +
        lineChart("Task throughput", "leases/s",
                  series((s) => s.task_rate_per_s || 0)) +
        lineChart("Object store", "MB",
                  series((s) => sumNodes(s, "store_mb"))) +
        lineChart("Workers", "",
                  series((s) => sumNodes(s, "workers"))) +
        "</div>";
    } else {
      charts = "<div class='note'>collecting history… " +
        "(first samples in a few seconds)</div>";
    }
    const rows = [];
    for (const line of text.split("\n")) {
      if (!line || line.startsWith("#")) continue;
      const sp = line.lastIndexOf(" ");
      rows.push({ metric: line.slice(0, sp), value: line.slice(sp + 1) });
    }
    return "<h1>Metrics</h1>" + charts +
      "<h2>Prometheus snapshot</h2>" +
      "<div class='note'><a href='/api/grafana/dashboard' target='_blank'>" +
      "generated Grafana dashboard JSON</a> · raw at <a href='/metrics' " +
      "target='_blank'>/metrics</a></div>" + renderTable(rows);
  },

  async stacks() {
    const stacks = await getJSON("/api/stacks");
    let h = "<h1>Worker stacks</h1>";
    for (const node of stacks) {
      for (const w of node.workers || []) {
        h += `<h2>worker ${esc((w.worker_id || "?").slice(0, 10))} ` +
          `(pid ${esc(w.pid)})</h2>`;
        if (w.error) h += `<pre class="logview">${esc(w.error)}</pre>`;
        for (const t of w.threads || []) {
          h += `<div class="note">${esc(t.thread)}` +
            (t.daemon ? " (daemon)" : "") + "</div>" +
            `<pre class="logview">${esc(t.stack)}</pre>`;
        }
      }
    }
    return h;
  },

  // Profiling is trigger-only (it samples live workers for N seconds);
  // auto-refresh must not re-trigger it, so the view renders a button.
  async profile() {
    return "<h1>CPU profile</h1>" +
      "<div class='note'>Statistical sampling of every live worker " +
      "(reference: dashboard reporter module's py-spy endpoint).</div>" +
      "<button id='profile-btn' data-dur='2'>profile 2 s</button> " +
      "<button id='profile-btn5' data-dur='5'>profile 5 s</button>" +
      "<div id='profile-out'></div>";
  },
};

// ---- time-series charts (vanilla SVG; single series per panel, so the
// accent hue carries no identity — the title names the series; hover
// crosshair shows the value at the nearest sample) ---------------------

let _chartSeq = 0;
const _chartData = {};

function lineChart(title, unit, pts, w = 380, h = 120) {
  const id = "ch" + (++_chartSeq);
  _chartData[id] = { pts, unit };
  const padL = 44, padR = 10, padT = 8, padB = 18;
  const xs = pts.map((p) => p.t), ys = pts.map((p) => p.v);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  let y0 = 0, y1 = Math.max(...ys);
  if (y1 <= y0) y1 = y0 + 1;
  y1 *= 1.08; // headroom so the line never kisses the frame
  const X = (t) => padL + (t - x0) / (x1 - x0 || 1) * (w - padL - padR);
  const Y = (v) => padT + (1 - (v - y0) / (y1 - y0)) * (h - padT - padB);
  const path = pts.map((p, i) =>
    (i ? "L" : "M") + X(p.t).toFixed(1) + " " + Y(p.v).toFixed(1)).join("");
  const last = pts[pts.length - 1];
  const fmt = (v) => v >= 100 ? Math.round(v) : +v.toFixed(1);
  // Two recessive gridlines at 1/3 and 2/3 of the scale.
  let g = "";
  for (const f of [1 / 3, 2 / 3]) {
    const yv = padT + (1 - f) * (h - padT - padB);
    g += `<line x1="${padL}" y1="${yv.toFixed(1)}" x2="${w - padR}" ` +
      `y2="${yv.toFixed(1)}" class="chart-grid-line"/>`;
  }
  const span = Math.round((x1 - x0) / 60);
  return `<div class="chart" data-chart="${id}">` +
    `<div class="chart-title">${esc(title)}` +
    `<span class="chart-last">${fmt(last.v)}${unit ? " " + esc(unit) : ""}` +
    `</span></div>` +
    `<svg viewBox="0 0 ${w} ${h}" data-w="${w}" data-h="${h}" ` +
    `data-padl="${padL}" data-padr="${padR}">` + g +
    `<line x1="${padL}" y1="${h - padB}" x2="${w - padR}" y2="${h - padB}" ` +
    `class="chart-axis"/>` +
    `<text x="${padL - 6}" y="${padT + 8}" class="chart-tick" ` +
    `text-anchor="end">${fmt(y1 / 1.08)}</text>` +
    `<text x="${padL - 6}" y="${h - padB}" class="chart-tick" ` +
    `text-anchor="end">0</text>` +
    `<text x="${padL}" y="${h - 4}" class="chart-tick">` +
    `${span ? "last " + span + " min" : "now"}</text>` +
    `<path d="${path}" class="chart-line"/>` +
    `<circle class="chart-dot" r="3.5" style="display:none"/>` +
    `<rect x="${padL}" y="0" width="${w - padL - padR}" height="${h}" ` +
    `fill="transparent" class="chart-hit"/>` +
    `</svg><div class="chart-tip" style="display:none"></div></div>`;
}

document.addEventListener("mousemove", (e) => {
  const hit = e.target.closest(".chart-hit");
  if (!hit) {
    for (const d of document.querySelectorAll(".chart-dot"))
      d.style.display = "none";
    for (const t of document.querySelectorAll(".chart-tip"))
      t.style.display = "none";
    return;
  }
  const box = hit.closest(".chart");
  const data = _chartData[box.dataset.chart];
  if (!data || !data.pts.length) return;
  const svg = box.querySelector("svg");
  const r = svg.getBoundingClientRect();
  const w = +svg.dataset.w, padL = +svg.dataset.padl,
    padR = +svg.dataset.padr;
  const fx = (e.clientX - r.left) / r.width * w;
  const pts = data.pts;
  const x0 = pts[0].t, x1 = pts[pts.length - 1].t;
  const t = x0 + (fx - padL) / (w - padL - padR) * (x1 - x0);
  let best = pts[0];
  for (const p of pts) if (Math.abs(p.t - t) < Math.abs(best.t - t)) best = p;
  const h = +svg.dataset.h;
  const X = padL + (best.t - x0) / (x1 - x0 || 1) * (w - padL - padR);
  const ys = pts.map((p) => p.v);
  const y1v = Math.max(...ys, 1) * 1.08;
  const Y = 8 + (1 - best.v / y1v) * (h - 8 - 18);
  const dot = box.querySelector(".chart-dot");
  dot.setAttribute("cx", X); dot.setAttribute("cy", Y);
  dot.style.display = "";
  const tip = box.querySelector(".chart-tip");
  tip.textContent = (+best.v.toFixed(2)) + (data.unit ? " " + data.unit : "") +
    " · " + new Date(best.t * 1000).toLocaleTimeString();
  tip.style.display = "";
  tip.style.left = Math.min(X / w * 100, 70) + "%";
});

async function runProfile(dur) {
  const out = $("profile-out");
  out.innerHTML = "<div class='note'>sampling…</div>";
  try {
    const nodes = await getJSON("/api/profile?duration=" + dur);
    let h = "";
    for (const node of nodes) {
      for (const w of node.workers || []) {
        const rows = (w.hot || []).map((t) => ({
          samples: t.count, frac: t.count && w.samples
            ? (100 * t.count / w.samples).toFixed(1) + "%" : "",
          stack: t.stack,
        }));
        h += `<h2>worker ${esc((w.worker_id || "?").slice(0, 10))} ` +
          `(pid ${esc(w.pid)}, ${esc(w.samples)} samples)</h2>` +
          renderTable(rows, { id: "prof-" + w.pid, noFilter: true });
      }
    }
    out.innerHTML = h || "<i>no samples</i>";
  } catch (e) {
    out.innerHTML = `<span style="color:var(--bad)">${esc(e)}</span>`;
  }
}

document.addEventListener("click", (e) => {
  const btn = e.target.closest("button[data-dur]");
  if (btn) runProfile(btn.dataset.dur);
});

// ---- router --------------------------------------------------------------

const NAV = [
  ["overview", "Overview"], ["nodes", "Nodes"], ["actors", "Actors"],
  ["tasks", "Tasks"], ["objects", "Objects"], ["pgs", "Placement groups"],
  ["jobs", "Jobs"], ["serve", "Serve"], ["workflows", "Workflows"],
  ["logs", "Logs"], ["events", "Events"], ["metrics", "Metrics"],
  ["stacks", "Stacks"], ["profile", "Profile"],
];

// Total (never throws): a malformed percent-escape in a hand-edited
// hash must not wedge the router — fall back to the raw segment.
function safeDecode(s) {
  try { return decodeURIComponent(s); } catch (e) { return s; }
}

function route() {
  const hash = location.hash.replace(/^#\//, "") || "overview";
  const parts = hash.split("/");
  // Longest-prefix match so "jobs/logs/<id>" resolves before "jobs".
  for (let n = parts.length; n > 0; n--) {
    const name = parts.slice(0, n).join("/");
    if (VIEWS[name]) return { name, args: parts.slice(n).map(safeDecode) };
  }
  return { name: "overview", args: [] };
}

let rendering = false;
let renderWaiters = null;
async function render() {
  // Coalesce, never drop: a nav/sort/filter event during an in-flight
  // refresh re-renders as soon as the current one finishes, and the
  // returned promise resolves only after THAT final render (callers
  // like the filter-box focus restore depend on it).
  if (rendering) {
    if (!renderWaiters) renderWaiters = [];
    return new Promise((res) => renderWaiters.push(res));
  }
  rendering = true;
  const { name, args } = route();
  for (const a of document.querySelectorAll("#nav-links a")) {
    a.classList.toggle("active", a.dataset.route === name.split("/")[0]);
  }
  $("crumbs").innerHTML = `<a href="#/overview">cluster</a> / ` +
    esc(name) + (args.length ? " / " + esc(args.join(" / ")) : "");
  try {
    const html = await VIEWS[name](...args);
    $("view").innerHTML = html;
    $("err").textContent = "";
    $("last-refresh").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    $("err").textContent = String(e);
  } finally {
    rendering = false;
    if (renderWaiters) {
      const waiters = renderWaiters;
      renderWaiters = null;
      render().then(() => waiters.forEach((res) => res()));
    }
  }
}

$("nav-links").innerHTML = NAV.map(([r, label]) =>
  `<a href="#/${r}" data-route="${r}">${label}</a>`).join("");

window.addEventListener("hashchange", render);
render();
setInterval(() => {
  // Don't wipe profile output (trigger-only view) on the timer.
  if ($("auto-refresh").checked && route().name !== "profile") render();
}, 3000);
