"""Distributed map-shuffle-reduce primitive + raysort-style benchmark.

Parity: python/ray/experimental/shuffle.py (the standalone two-stage
shuffle the reference uses to exercise the object store at scale) and
raysort (the sort benchmark built on it). Map tasks partition their
input into R objects each; reduce task j consumes partition j of every
map — all M*R intermediate objects move through the object store /
transfer plane, never the driver.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import ray_tpu


def shuffle(num_maps: int, num_reduces: int, map_fn: Callable,
            reduce_fn: Callable) -> list:
    """map_fn(map_index, num_reduces) -> list of num_reduces partitions;
    reduce_fn(reduce_index, partitions) -> result. Returns the reduce
    results in order."""

    @ray_tpu.remote
    def _map(i, r):
        parts = map_fn(i, r)
        assert len(parts) == r, "map_fn must return num_reduces partitions"
        return tuple(parts) if r > 1 else parts[0]

    @ray_tpu.remote
    def _reduce(j, *parts):
        return reduce_fn(j, list(parts))

    # num_returns: each partition is its OWN object, so reduce j pulls
    # exactly partition j of every map — not the whole map output R
    # times (the reference shuffle's layout).
    map_refs = [_map.options(num_returns=num_reduces).remote(i, num_reduces)
                for i in range(num_maps)]
    if num_reduces == 1:
        map_refs = [[m] for m in map_refs]
    out = []
    for j in range(num_reduces):
        out.append(_reduce.remote(j, *[m[j] for m in map_refs]))
    return ray_tpu.get(out, timeout=1200)


def raysort(total_items: int, *, num_maps: int = 4, num_reduces: int = 4,
            seed: int = 0) -> dict:
    """Distributed sort benchmark (parity: experimental/raysort): random
    u64 keys are range-partitioned by the maps, each reduce sorts its
    range; validates global order and returns throughput stats."""
    import time

    per_map = total_items // num_maps
    t0 = time.perf_counter()

    def map_fn(i, r):
        rng = np.random.default_rng(seed + i)
        data = rng.integers(0, 2 ** 62, per_map, dtype=np.uint64)
        bounds = np.linspace(0, 2 ** 62, r + 1)
        return [data[(data >= bounds[j]) & (data < bounds[j + 1])]
                for j in range(r)]

    def reduce_fn(j, parts):
        merged = np.concatenate(parts)
        merged.sort()
        return merged

    ranges = shuffle(num_maps, num_reduces, map_fn, reduce_fn)
    dt = time.perf_counter() - t0

    # Validate: each range sorted, ranges ordered, count preserved.
    n = 0
    prev_max = -1
    for rng_sorted in ranges:
        if len(rng_sorted):
            assert np.all(np.diff(rng_sorted.astype(np.int64)) >= 0)
            assert int(rng_sorted[0]) >= prev_max
            prev_max = int(rng_sorted[-1])
        n += len(rng_sorted)
    assert n == per_map * num_maps
    return {"items_sorted": n, "wall_s": round(dt, 3),
            "items_per_s": round(n / dt, 1)}
