"""Distributed progress bars (parity: python/ray/experimental/tqdm_ray).

Workers cannot draw terminal progress bars — their stdout is a log file
tailed to the driver, and N workers would interleave N carriage-return
streams. The reference's answer: workers emit structured progress
records; the DRIVER owns the terminal and multiplexes one bar per
(worker, description). Here the records ride the existing LOGS pubsub
channel as magic-prefixed lines, so no new plumbing is needed and bars
survive worker death like any other log line.
"""

from __future__ import annotations

import json
import sys
import threading

MAGIC = "__ray_tpu_tqdm__:"

_renderer_lock = threading.Lock()
_renderer = None


def _in_driver() -> bool:
    try:
        from ray_tpu._private.api_internal import get_core_worker

        return bool(get_core_worker().is_driver)
    except Exception:
        # Not connected: a plain process owns its own terminal too.
        return True


def _driver_renderer() -> "DriverSideRenderer":
    global _renderer
    with _renderer_lock:
        if _renderer is None:
            _renderer = DriverSideRenderer()
        return _renderer


class tqdm:
    """Drop-in subset of tqdm's API for use inside tasks/actors (and the
    driver). In a worker, updates print magic lines the driver renders;
    on the driver, updates draw directly."""

    def __init__(self, iterable=None, desc: str = "", total: int | None = None,
                 position: int | None = None):
        self.iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self._closed = False
        self._emit()

    def __iter__(self):
        for x in self.iterable:
            yield x
            self.update(1)
        self.close()

    def update(self, n: int = 1):
        self.n += n
        self._emit()

    def set_description(self, desc: str):
        self.desc = desc
        self._emit()

    def close(self):
        if not self._closed:
            self._closed = True
            self._emit(closed=True)

    def _emit(self, closed: bool = False):
        rec = {"desc": self.desc, "n": self.n, "total": self.total,
               "closed": closed, "id": id(self)}
        if _in_driver():
            # Driver owns the terminal: render directly instead of
            # emitting a record nobody would consume.
            _driver_renderer().maybe_render(
                "driver", MAGIC + json.dumps(rec))
        else:
            print(MAGIC + json.dumps(rec), flush=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _BarState:
    __slots__ = ("desc", "n", "total")

    def __init__(self):
        self.desc = ""
        self.n = 0
        self.total = None


class DriverSideRenderer:
    """Driver-side multiplexer: feed it raw log lines (the driver's log
    subscriber calls maybe_render per line); magic lines update bars
    drawn on one terminal region, everything else passes through."""

    def __init__(self, out=None):
        self.out = out or sys.stderr
        self._bars: dict[tuple, _BarState] = {}
        self._lock = threading.Lock()

    def maybe_render(self, worker_id: str, line: str) -> bool:
        """True if the line was a progress record (consumed)."""
        idx = line.find(MAGIC)
        if idx < 0:
            return False
        try:
            rec = json.loads(line[idx + len(MAGIC):])
        except ValueError:
            return False
        key = (worker_id, rec.get("id"))
        with self._lock:
            if rec.get("closed"):
                self._bars.pop(key, None)
            else:
                bar = self._bars.setdefault(key, _BarState())
                bar.desc = rec.get("desc", "")
                bar.n = rec.get("n", 0)
                bar.total = rec.get("total")
            self._draw()
        return True

    def _draw(self):
        parts = []
        for (wid, _bid), bar in self._bars.items():
            if bar.total:
                pct = 100.0 * bar.n / max(bar.total, 1)
                parts.append(f"{bar.desc or wid[:6]}: "
                             f"{bar.n}/{bar.total} ({pct:.0f}%)")
            else:
                parts.append(f"{bar.desc or wid[:6]}: {bar.n}")
        if parts:
            self.out.write("\r" + " | ".join(parts) + "\x1b[K")
            self.out.flush()
