"""Experimental utilities (parity: reference python/ray/experimental/)."""

from __future__ import annotations

from ray_tpu._private.api_internal import get_core_worker


class internal_kv:
    """Direct access to the GCS KV store (parity:
    python/ray/experimental/internal_kv.py)."""

    @staticmethod
    def _kv_put(key: bytes, value: bytes, overwrite: bool = True,
                namespace: str = "") -> bool:
        cw = get_core_worker()
        return cw._run(cw.gcs.call("KVPut", {
            "ns": namespace, "key": key, "value": value,
            "overwrite": overwrite}))["added"]

    @staticmethod
    def _kv_get(key: bytes, namespace: str = "") -> bytes | None:
        cw = get_core_worker()
        return cw._run(cw.gcs.call("KVGet", {"ns": namespace, "key": key}))["value"]

    @staticmethod
    def _kv_del(key: bytes, namespace: str = "") -> bool:
        cw = get_core_worker()
        return cw._run(cw.gcs.call("KVDel", {"ns": namespace, "key": key}))["deleted"]

    @staticmethod
    def _kv_exists(key: bytes, namespace: str = "") -> bool:
        cw = get_core_worker()
        return cw._run(cw.gcs.call("KVExists", {"ns": namespace, "key": key}))["exists"]

    @staticmethod
    def _kv_list(prefix: bytes, namespace: str = "") -> list[bytes]:
        cw = get_core_worker()
        return cw._run(cw.gcs.call("KVKeys", {"ns": namespace, "prefix": prefix}))["keys"]


from ray_tpu.experimental import tqdm_ray  # noqa: E402,F401
from ray_tpu.experimental.shuffle import raysort, shuffle  # noqa: E402,F401
