"""MongoDB connector.

Parity: reference read_mongo / Dataset.write_mongo
(python/ray/data/read_api.py read_mongo, datasource/mongo_datasource.py
— partitioned reads via an aggregation pipeline, writes via
insert_many). The driver dependency is injectable: `client_factory` is
any zero-arg picklable callable returning a pymongo-compatible client
(client[db][coll].aggregate / .count_documents / .insert_many), so the
connector works with pymongo when installed and with hermetic fakes in
tests — the image ships no mongo server or driver.
"""

from __future__ import annotations

import functools


def _default_client(uri: str):
    try:
        import pymongo
    except ImportError as e:  # pragma: no cover - driver not in image
        raise ImportError(
            "read_mongo/write_mongo need pymongo (not installed) or an "
            "explicit client_factory") from e
    return pymongo.MongoClient(uri)


def _fetch(factory, database, collection, pipeline, skip, limit):
    client = factory()
    coll = client[database][collection]
    stages = list(pipeline or [])
    # $skip/$limit append AFTER the user pipeline so filters/projections
    # inside it see the whole collection; deterministic shard boundaries
    # need a stable order, so sort by _id first when sharding.
    if skip is not None:
        stages = [{"$sort": {"_id": 1}}] + stages + \
            [{"$skip": skip}, {"$limit": limit}]
    return [dict(d) for d in coll.aggregate(stages)]


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: list | None = None,
               override_num_blocks: int | None = None,
               client_factory=None):
    """Dataset over a MongoDB collection, optionally through an
    aggregation `pipeline`. With override_num_blocks=N>1 the (sorted by
    _id) result is sharded into N skip/limit ranges read as independent
    cluster tasks (the reference partitions the same collection scan
    across read tasks)."""
    from ray_tpu.data.dataset import Dataset, ReadTask

    factory = client_factory or functools.partial(_default_client, uri)
    n = override_num_blocks or 1
    # Sharding slices a stable _id order with $skip/$limit, which is
    # only correct when the user pipeline maps documents independently —
    # stages like $group/$sort/$unwind emit results in their own
    # (possibly nondeterministic) order, so the N independent aggregate
    # calls would slice N DIFFERENT orderings and duplicate/drop rows.
    _ORDER_PRESERVING = {"$match", "$project", "$addFields", "$set",
                         "$unset", "$redact"}
    if n > 1 and pipeline and any(
            next(iter(st)) not in _ORDER_PRESERVING for st in pipeline):
        n = 1
    if n > 1:
        client = factory()
        coll = client[database][collection]
        if pipeline:
            counted = list(coll.aggregate(list(pipeline)
                                          + [{"$count": "n"}]))
            total = counted[0]["n"] if counted else 0
        else:
            total = coll.count_documents({})
        per = -(-total // n) if total else 0
        tasks = []
        for i in range(n):
            skip = i * per
            # per=0 (empty source) or skip>=total would send MongoDB a
            # rejected {$limit: 0} / read nothing: stop emitting tasks.
            if per <= 0 or skip >= total:
                break
            tasks.append(ReadTask(
                fn=functools.partial(_fetch, factory, database,
                                     collection, pipeline, skip, per),
                num_rows=min(per, total - skip),
                meta={"kind": "mongo", "database": database,
                      "collection": collection, "skip": skip,
                      "limit": per}))
        if tasks:
            return Dataset(tasks)
    return Dataset([ReadTask(
        fn=functools.partial(_fetch, factory, database, collection,
                             pipeline, None, None),
        meta={"kind": "mongo", "database": database,
              "collection": collection})])


def _write_block(factory, database, collection, rows):
    if rows:
        client = factory()
        client[database][collection].insert_many(list(rows))
    return len(rows)


def write_mongo(ds, uri: str, database: str, collection: str, *,
                client_factory=None) -> int:
    """Insert every row of `ds` into the collection (one insert_many per
    block, run as cluster tasks); returns rows written."""
    import ray_tpu
    from ray_tpu.data.block import block_to_rows

    from ray_tpu.data.context import DataContext

    factory = client_factory or functools.partial(_default_client, uri)

    @ray_tpu.remote
    def write_one(block):
        return _write_block(factory, database, collection,
                            block_to_rows(block))

    # Windowed submission (like the executor's run_segment): bounded
    # driver memory and bounded concurrent bulk inserts on the server.
    window_size = DataContext.get_current().max_in_flight_blocks
    total = 0
    window: list = []
    for block in ds._iter_output_blocks():
        window.append(write_one.remote(block))
        if len(window) >= window_size:
            total += ray_tpu.get(window.pop(0))
    for ref in window:
        total += ray_tpu.get(ref)
    return total
