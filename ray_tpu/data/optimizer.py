"""Logical-plan optimizer: an explicit rule catalog applied to every
Dataset plan before physical execution.

Parity: reference python/ray/data/_internal/logical/rules/ — the rule
catalog (operator_fusion.py, limit_pushdown.py, randomize_blocks.py,
zero_copy_map_fusion.py, _user_provided_optimizer_rules.py) driven by
the LogicalOptimizer in _internal/logical/optimizers.py. Here the
logical plan IS the (source, stages) pair the Dataset holds, so rules
are plain plan -> plan rewrites:

- ParquetReadPushdown: fold leading projections/predicates into the
  parquet ReadTasks (pyarrow prunes columns + row groups at the file).
- MergeProjections: collapse adjacent column selections into the
  narrower one.
- DropRedundantRandomize: a randomize_block_order made irrelevant by a
  later random_shuffle (or a later randomize) is deleted.
- ReorderRandomizeBlocks: bubble randomize_block_order toward the
  source past per-block map stages so it never splits a fusable map
  chain and permutes lazy refs, not materialized blocks (reference:
  randomize_blocks.py ReorderRandomizeBlocksRule).
- FuseMapStages: collapse adjacent compatible per-block map stages into
  one stage at the LOGICAL level (reference: operator_fusion.py). The
  executor additionally fuses whatever remains adjacent at runtime —
  this rule makes the fusion decision visible in Dataset.explain().

User-provided rules (reference: _user_provided_optimizer_rules.py)
append after the built-ins via register_optimizer_rule(), or replace
the whole catalog via DataContext.optimizer_rules.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass
class LogicalPlan:
    """(source blocks/ReadTasks, stage list) — the unit rules rewrite."""

    source: list
    stages: list


class Rule:
    """A logical-plan rewrite; must preserve semantics, not cost."""

    name = "rule"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        raise NotImplementedError


def _is_plain_map(st) -> bool:
    """Per-block task-mapped stage: safe to fuse with neighbours and to
    commute with block-order changes."""
    return (not st.all_to_all and st.shuffle_map_fn is None
            and not st.actor_pool and not getattr(st, "reorder", False))


class ParquetReadPushdown(Rule):
    """Fold leading projection/predicate stages into parquet ReadTasks
    (reference: the logical optimizer's pushdown rules run before
    physical planning)."""

    name = "parquet_read_pushdown"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        from ray_tpu.data.dataset import ReadTask

        source, stages = plan.source, plan.stages
        if not source or not all(
                isinstance(s, ReadTask) and s.meta
                and s.meta.get("kind") == "parquet" for s in source):
            return plan
        metas = [dict(s.meta) for s in source]
        i = 0
        for st in stages:
            # Fold only when transparent: a projection/predicate
            # referencing a column OUTSIDE the current projection must
            # keep its stage (which raises KeyError at runtime) —
            # folding it into pyarrow would silently succeed, diverging
            # from the non-parquet path.
            current_cols = metas[0].get("columns")
            if st.pushdown_projection is not None:
                cols = st.pushdown_projection
                if current_cols is not None and \
                        not set(cols) <= set(current_cols):
                    break
                for m in metas:
                    m["columns"] = list(cols)
            elif st.pushdown_filter is not None:
                col, _op, _lit = st.pushdown_filter
                if current_cols is not None and col not in current_cols:
                    break
                for m in metas:
                    m["filters"] = (m.get("filters") or []) + \
                        [tuple(st.pushdown_filter)]
            else:
                break
            i += 1
        if i == 0:
            return plan
        import functools

        from ray_tpu.data import _read_parquet_group  # late: avoid cycle

        new_source = [
            ReadTask(fn=functools.partial(
                _read_parquet_group, m["group"], m.get("columns"),
                m.get("filters"), m.get("endpoint_url")), meta=m)
            for m in metas]
        return LogicalPlan(new_source, stages[i:])


class MergeProjections(Rule):
    """Adjacent column selections collapse into the later (narrower)
    one when it only references columns the earlier kept — the runtime
    KeyError contract is unchanged because the later selection would
    fail on those columns anyway."""

    name = "merge_projections"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        stages = list(plan.stages)
        i = 0
        while i + 1 < len(stages):
            a, b = stages[i], stages[i + 1]
            if (a.pushdown_projection is not None
                    and b.pushdown_projection is not None
                    and set(b.pushdown_projection)
                    <= set(a.pushdown_projection)):
                del stages[i]
            else:
                i += 1
        return LogicalPlan(plan.source, stages)


class DropRedundantRandomize(Rule):
    """randomize_block_order is a no-op when a later random_shuffle (a
    full row-level shuffle) or a later randomize runs anyway (reference:
    randomize_blocks.py drops the op under the same conditions)."""

    name = "drop_redundant_randomize"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        stages = list(plan.stages)
        out = []
        for i, st in enumerate(stages):
            if getattr(st, "reorder", False) and any(
                    getattr(later, "reorder", False)
                    or later.name == "random_shuffle"
                    for later in stages[i + 1:]):
                continue
            out.append(st)
        return LogicalPlan(plan.source, out)


class ReorderRandomizeBlocks(Rule):
    """Bubble randomize_block_order toward the SOURCE past per-block map
    stages (maps apply to every block regardless of order, so the swap
    is semantics-free; reference: ReorderRandomizeBlocksRule). Two wins:
    the map chain becomes adjacent for fusion, and the reorder barrier
    lands where blocks are still lazy ObjectRefs — permuting refs is
    free, while a reorder AFTER maps would buffer every materialized
    block at the barrier."""

    name = "reorder_randomize_blocks"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        stages = list(plan.stages)
        changed = True
        while changed:
            changed = False
            for i in range(len(stages) - 1):
                if (_is_plain_map(stages[i])
                        and getattr(stages[i + 1], "reorder", False)):
                    stages[i], stages[i + 1] = stages[i + 1], stages[i]
                    changed = True
        return LogicalPlan(plan.source, stages)


def _compose(f, g):
    def fused(block, f=f, g=g):
        return g(f(block))

    return fused


class FuseMapStages(Rule):
    """Collapse adjacent compatible per-block maps into one logical
    stage (reference: operator_fusion.py — same compute strategy, same
    resource request). The fused stage costs one task and zero
    intermediate objects per block."""

    name = "fuse_map_stages"

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        stages = list(plan.stages)
        out: list = []
        for st in stages:
            prev = out[-1] if out else None
            if (prev is not None and _is_plain_map(prev)
                    and _is_plain_map(st)
                    and prev.num_cpus == st.num_cpus):
                out[-1] = replace(
                    prev, name=f"{prev.name}->{st.name}",
                    fn=_compose(prev.fn, st.fn),
                    # Pushdown tags describe the ORIGINAL single-purpose
                    # stage; a fused body is opaque to later rules.
                    pushdown_projection=None, pushdown_filter=None)
            else:
                out.append(st)
        return LogicalPlan(plan.source, out)


def default_rules() -> list[Rule]:
    # Order matters: pushdown first (it needs the original per-stage
    # tags), then projection merging, then the randomize rewrites, then
    # fusion (which erases the tags it consumes).
    return [ParquetReadPushdown(), MergeProjections(),
            DropRedundantRandomize(), ReorderRandomizeBlocks(),
            FuseMapStages()]


_user_rules: list[Rule] = []


def register_optimizer_rule(rule: Rule) -> None:
    """Append a user rule after the built-in catalog (reference:
    _user_provided_optimizer_rules.py)."""
    _user_rules.append(rule)


def optimize(plan: LogicalPlan) -> LogicalPlan:
    """Run the catalog (DataContext.optimizer_rules overrides the
    built-ins when set) plus registered user rules."""
    from ray_tpu.data.context import DataContext

    rules = DataContext.get_current().optimizer_rules
    if rules is None:
        rules = default_rules()
    for rule in list(rules) + _user_rules:
        plan = rule.apply(plan)
    return plan
