"""Minimal S3-protocol object-store client for Dataset IO.

Parity target: the reference reads cloud storage through pyarrow
filesystems + per-datasource glue (reference: python/ray/data/datasource/,
tested hermetically against a local mock server —
data/tests/mock_s3_server.py). This image has no boto3 and zero egress,
so the client is stdlib http.client speaking the two S3 REST calls
Dataset IO needs: ListObjectsV2 and GetObject. It targets S3-COMPATIBLE
endpoints (set ``RAY_TPU_S3_ENDPOINT`` or pass ``endpoint_url=``) —
SigV4-signed AWS auth is out of scope; compatible stores (minio-style,
the test mock) accept anonymous reads.

URI form: ``s3://bucket/key-or-prefix``.
"""

from __future__ import annotations

import http.client
import io
import os
import urllib.parse
import xml.etree.ElementTree as ET

ENDPOINT_ENV = "RAY_TPU_S3_ENDPOINT"


def is_s3_uri(path: str) -> bool:
    return isinstance(path, str) and path.startswith("s3://")


def parse_uri(uri: str) -> tuple[str, str]:
    rest = uri[len("s3://"):]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"malformed s3 uri {uri!r}")
    return bucket, key


class S3Client:
    def __init__(self, endpoint_url: str | None = None):
        endpoint_url = endpoint_url or os.environ.get(ENDPOINT_ENV)
        if not endpoint_url:
            raise ValueError(
                "s3:// paths need an endpoint: pass endpoint_url= or set "
                f"{ENDPOINT_ENV} (SigV4 AWS auth is not supported; use an "
                "S3-compatible endpoint)")
        u = urllib.parse.urlparse(endpoint_url)
        if u.scheme not in ("http", "https"):
            raise ValueError(f"unsupported endpoint scheme {u.scheme!r}")
        self._https = u.scheme == "https"
        self._host = u.hostname
        self._port = u.port or (443 if self._https else 80)

    def _conn(self) -> http.client.HTTPConnection:
        cls = (http.client.HTTPSConnection if self._https
               else http.client.HTTPConnection)
        return cls(self._host, self._port, timeout=60)

    def list_keys(self, bucket: str, prefix: str = "") -> list[str]:
        """ListObjectsV2 with continuation support."""
        keys: list[str] = []
        token = None
        while True:
            q = {"list-type": "2", "prefix": prefix}
            if token:
                q["continuation-token"] = token
            conn = self._conn()
            try:
                conn.request(
                    "GET", f"/{bucket}?{urllib.parse.urlencode(q)}")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise IOError(
                        f"s3 list {bucket!r} prefix={prefix!r} -> "
                        f"{resp.status}: {body[:200]!r}")
            finally:
                conn.close()
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag.split("}")[0] + "}"
            for c in root.findall(f"{ns}Contents"):
                k = c.find(f"{ns}Key")
                if k is not None and k.text:
                    keys.append(k.text)
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None or (trunc.text or "").lower() != "true":
                break
            tok = root.find(f"{ns}NextContinuationToken")
            token = tok.text if tok is not None else None
            if not token:
                break
        return keys

    def get_object(self, bucket: str, key: str,
                   byte_range: tuple[int, int] | None = None) -> bytes:
        headers = {}
        if byte_range is not None:
            headers["Range"] = f"bytes={byte_range[0]}-{byte_range[1]}"
        conn = self._conn()
        try:
            conn.request(
                "GET", f"/{bucket}/{urllib.parse.quote(key)}",
                headers=headers)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status not in (200, 206):
                raise FileNotFoundError(
                    f"s3://{bucket}/{key}: {resp.status} {body[:200]!r}")
            return body
        finally:
            conn.close()


def expand_uri(uri: str, endpoint_url: str | None = None) -> list[str]:
    """Expand an s3:// prefix into the full object URIs under it."""
    bucket, prefix = parse_uri(uri)
    client = S3Client(endpoint_url)
    return [f"s3://{bucket}/{k}" for k in client.list_keys(bucket, prefix)]


def open_uri(path: str, endpoint_url: str | None = None) -> io.BytesIO:
    """Fetch an object into a seekable buffer (parquet readers seek)."""
    bucket, key = parse_uri(path)
    return io.BytesIO(S3Client(endpoint_url).get_object(bucket, key))
