"""SQL + webdataset connectors.

Parity: reference read_sql (python/ray/data/read_api.py — any DBAPI2
connection factory; partitioned by sharding the query) and the
webdataset datasource (tar shards of samples grouped by key, decoded by
extension). Both are dependency-free: DBAPI2 is a protocol (sqlite3 in
the stdlib satisfies it; any installed driver works), and tar shards
read with the stdlib tarfile module.
"""

from __future__ import annotations

import io
import json
import tarfile


def read_sql(sql: str, connection_factory, *,
             override_num_blocks: int | None = None):
    """Dataset from a SQL query via a DBAPI2 connection factory.

    `connection_factory` is a zero-arg callable returning a DBAPI2
    connection — it must be picklable (reads run as cluster tasks), so
    pass a module-level function or functools.partial, not a live
    connection. Parallelism: with override_num_blocks=N>1 the query is
    sharded as `SELECT * FROM (<sql>) LIMIT ... OFFSET ...` per block
    (the reference shards identically); N=1/None runs it whole.
    """
    from ray_tpu.data.dataset import Dataset, ReadTask

    def fetch(query: str, params=()):
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(query, params)
            cols = [d[0] for d in cur.description]
            return [dict(zip(cols, row)) for row in cur.fetchall()]
        finally:
            conn.close()

    n = override_num_blocks or 1
    if n <= 1:
        return Dataset([ReadTask(fn=lambda: fetch(sql),
                                 meta={"kind": "sql", "sql": sql})])

    def count():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT COUNT(*) FROM ({sql})")
            return int(cur.fetchone()[0])
        finally:
            conn.close()

    total = count()
    per = max(1, -(-total // n))
    tasks = []
    for i in range(n):
        off = i * per
        if off >= total:
            break
        shard_sql = f"SELECT * FROM ({sql}) LIMIT {per} OFFSET {off}"
        tasks.append(ReadTask(
            fn=(lambda q=shard_sql: fetch(q)),
            num_rows=min(per, total - off),
            meta={"kind": "sql", "sql": shard_sql}))
    return Dataset(tasks)


# extension -> decoder for webdataset samples (reference default_decoder)
def _decode_member(ext: str, data: bytes):
    ext = ext.lower()
    if ext in ("txt", "text"):
        return data.decode("utf-8", errors="replace")
    if ext == "json":
        return json.loads(data)
    if ext in ("cls", "cls2", "index", "id"):
        try:
            return int(data.decode().strip())
        except ValueError:
            return data.decode(errors="replace").strip()
    if ext in ("jpg", "jpeg", "png", "ppm", "bmp"):
        try:
            import numpy as np
            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)))
        except ImportError:
            return data
    if ext in ("npy",):
        import numpy as np

        return np.load(io.BytesIO(data), allow_pickle=False)
    return data  # unknown extension: raw bytes


def read_webdataset(paths, *, override_num_blocks: int | None = None,
                    decode: bool = True):
    """Dataset over webdataset-style tar shards.

    Each tar member `key.ext` contributes field `ext` to the sample
    `key` (reference: webdataset_datasource — samples are consecutive
    members sharing a basename); one block per shard. `decode=False`
    yields raw bytes per field.
    """
    from ray_tpu.data import _expand, _lazy_read

    def read_one(path):
        samples: dict[str, dict] = {}
        order: list[str] = []
        with tarfile.open(path) as tf:
            for m in tf:
                if not m.isfile():
                    continue
                name = m.name
                key, _, ext = name.rpartition(".")
                if not key:
                    key, ext = name, ""
                data = tf.extractfile(m).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                samples[key][ext] = (_decode_member(ext, data)
                                     if decode else data)
        return [samples[k] for k in order]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)
