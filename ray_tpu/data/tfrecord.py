"""TFRecord IO: record framing + a minimal tf.train.Example codec.

Parity: reference `ray.data.read_tfrecords` / `Dataset.write_tfrecords`
(python/ray/data/read_api.py, datasource/tfrecords_datasource.py — the
reference parses Examples via TensorFlow). This build has no TensorFlow
and no generated protobuf classes, so both layers are implemented
directly against the public formats:

- TFRecord framing: [u64 length][u32 masked crc32c(length)]
  [data][u32 masked crc32c(data)], little-endian, CRC32C (Castagnoli)
  with the TF mask ((crc >> 15 | crc << 17) + 0xa282ead8).
- tf.train.Example protobuf wire format: Example{ features:
  Features{ feature: map<string, Feature> } }, Feature one of
  BytesList/FloatList/Int64List. Only these shapes exist in the
  message, so a tiny varint/length-delimited codec covers the format.

Scalar lists of length 1 flatten to scalars on read (the reference
does the same); floats are float32 per the proto type.
"""

from __future__ import annotations

import struct

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven, with TensorFlow's masking.
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        tbl = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    tbl = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Record framing
# ---------------------------------------------------------------------------


def read_records(src, *, verify: bool = False):
    """Yield raw record payloads from one TFRecord file. `src` is a path
    or a binary file-like object (s3:// readers pass the latter).
    `verify` checks the CRCs (off by default: pure-Python CRC costs
    ~1 MB/ms and the length CRC already catches truncation)."""
    import contextlib

    path = src if isinstance(src, str) else getattr(src, "name", "<stream>")
    ctx = (open(src, "rb") if isinstance(src, str)
           else contextlib.nullcontext(src))
    with ctx as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify and _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"{path}: corrupt record length CRC")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"{path}: truncated record")
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:  # cut between payload and its CRC
                raise ValueError(f"{path}: truncated record")
            (data_crc,) = struct.unpack("<I", crc_bytes)
            if verify and _masked_crc(data) != data_crc:
                raise ValueError(f"{path}: corrupt record data CRC")
            yield data


def write_records(path: str, payloads) -> int:
    """Write raw payloads as framed TFRecords. Returns the count."""
    n = 0
    with open(path, "wb") as f:
        for data in payloads:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))
            n += 1
    return n


# ---------------------------------------------------------------------------
# Minimal protobuf wire codec for tf.train.Example
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    end = len(buf)
    while True:
        if pos >= end:  # malformed message: varint runs past the buffer
            raise ValueError("malformed protobuf: truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 2:          # length-delimited
            n, pos = _read_varint(buf, pos)
            yield field, wt, buf[pos:pos + n]
            pos += n
        elif wt == 0:        # varint
            v, pos = _read_varint(buf, pos)
            yield field, wt, v
        elif wt == 5:        # fixed32
            yield field, wt, buf[pos:pos + 4]
            pos += 4
        elif wt == 1:        # fixed64
            yield field, wt, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")


def _signed64(v: int) -> int:
    """int64 fields are plain two's-complement varints; sign-extend."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_feature(buf: bytes):
    """Feature { BytesList=1 / FloatList=2 / Int64List=3 }."""
    for field, _wt, val in _fields(buf):
        if field == 1:       # BytesList { repeated bytes value = 1 }
            return [v for f, _w, v in _fields(val) if f == 1]
        if field == 2:       # FloatList { repeated float value = 1 [packed] }
            out = []
            for f, w, v in _fields(val):
                if f != 1:
                    continue
                if w == 2:   # packed
                    out.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:        # unpacked fixed32
                    out.append(struct.unpack("<f", v)[0])
            return out
        if field == 3:       # Int64List { repeated int64 value = 1 [packed] }
            out = []
            for f, w, v in _fields(val):
                if f != 1:
                    continue
                if w == 2:   # packed varints
                    pos = 0
                    while pos < len(v):
                        x, pos = _read_varint(v, pos)
                        out.append(_signed64(x))
                else:
                    out.append(_signed64(v))
            return out
    return []


def parse_example(payload: bytes) -> dict:
    """tf.train.Example -> {name: scalar | list}. Length-1 lists flatten
    to scalars (reference behavior)."""
    row: dict = {}
    for field, _wt, val in _fields(payload):
        if field != 1:       # Example.features
            continue
        for f2, _w2, entry in _fields(val):
            if f2 != 1:      # Features.feature map entries
                continue
            name, feature = None, b""
            for f3, _w3, v3 in _fields(entry):
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feature = v3
            if name is None:
                continue
            vals = _parse_feature(feature)
            row[name] = vals[0] if len(vals) == 1 else vals
    return row


def _encode_feature(values) -> bytes:
    """values -> Feature bytes. bytes/str -> BytesList, any float ->
    FloatList, int/bool -> Int64List. Mixed int/float lists promote to
    FloatList; anything else (nested lists, mixed str/number) is a
    ValueError rather than silent corruption."""
    if not isinstance(values, (list, tuple)):
        values = [values]
    if any(isinstance(v, (list, tuple)) for v in values):
        raise ValueError(
            "tf.train.Example features are flat lists; nested lists / "
            "multi-dimensional tensors are not encodable (flatten the "
            "column first)")
    is_str = [isinstance(v, (bytes, str)) for v in values]
    if any(is_str) and not all(is_str):
        raise ValueError(f"mixed bytes/str and numeric feature: {values!r}")
    if not all(is_str) and any(isinstance(v, float) for v in values):
        # Promote int members instead of silently truncating floats.
        values = [float(v) for v in values]
    inner = bytearray()
    if values and isinstance(values[0], (bytes, str)):
        for v in values:
            b = v.encode() if isinstance(v, str) else v
            inner.append((1 << 3) | 2)
            _write_varint(inner, len(b))
            inner.extend(b)
        field = 1
    elif values and isinstance(values[0], float):
        packed = struct.pack(f"<{len(values)}f", *values)
        inner.append((1 << 3) | 2)
        _write_varint(inner, len(packed))
        inner.extend(packed)
        field = 2
    else:
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        inner.append((1 << 3) | 2)
        _write_varint(inner, len(packed))
        inner.extend(packed)
        field = 3
    out = bytearray()
    out.append((field << 3) | 2)
    _write_varint(out, len(inner))
    out.extend(inner)
    return bytes(out)


def encode_example(row: dict) -> bytes:
    """{name: value(s)} -> serialized tf.train.Example."""
    features = bytearray()
    for name, values in row.items():
        entry = bytearray()
        nb = name.encode()
        entry.append((1 << 3) | 2)          # key
        _write_varint(entry, len(nb))
        entry.extend(nb)
        fb = _encode_feature(values)
        entry.append((2 << 3) | 2)          # value (Feature)
        _write_varint(entry, len(fb))
        entry.extend(fb)
        features.append((1 << 3) | 2)       # Features.feature entry
        _write_varint(features, len(entry))
        features.extend(entry)
    out = bytearray()
    out.append((1 << 3) | 2)                # Example.features
    _write_varint(out, len(features))
    out.extend(features)
    return bytes(out)
