"""ray_tpu.data: block-parallel datasets with streaming execution.

Parity: reference python/ray/data/__init__.py read APIs (range:*,
from_items, read_*, from_pandas/numpy).
"""

from __future__ import annotations

import builtins as _builtins
import glob as _glob
import math
from typing import Any, Iterable

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import DataIterator, Dataset, GroupedData

DEFAULT_BLOCK_COUNT = 8


def _to_blocks(rows: list, num_blocks: int | None) -> list:
    n = num_blocks or min(DEFAULT_BLOCK_COUNT, max(1, len(rows)))
    per = math.ceil(len(rows) / n) if rows else 0
    blocks = [rows[i * per:(i + 1) * per] for i in _builtins.range(n)]
    return [b for b in blocks if b] or [[]]


def from_items(items: list, *, override_num_blocks: int | None = None) -> Dataset:
    return Dataset(_to_blocks(list(items), override_num_blocks))


def range(n: int, *, override_num_blocks: int | None = None) -> Dataset:  # noqa: A001
    return from_items(list(_builtins.range(n)),
                      override_num_blocks=override_num_blocks)


def range_tensor(n: int, *, shape: tuple = (1,),
                 override_num_blocks: int | None = None) -> Dataset:
    rows = [{"data": np.full(shape, i, dtype=np.int64)}
            for i in _builtins.range(n)]
    return from_items(rows, override_num_blocks=override_num_blocks)


def from_numpy(arr: "np.ndarray", *, column: str = "data",
               override_num_blocks: int | None = None) -> Dataset:
    rows = [{column: a} for a in arr]
    return from_items(rows, override_num_blocks=override_num_blocks)


def from_pandas(df, *, override_num_blocks: int | None = None) -> Dataset:
    rows = df.to_dict("records")
    return from_items(rows, override_num_blocks=override_num_blocks)


def read_text(paths: str | list, *, override_num_blocks: int | None = None
              ) -> Dataset:
    files = _expand(paths)
    rows = []
    for p in files:
        with open(p) as f:
            rows.extend({"text": line.rstrip("\n")} for line in f)
    return from_items(rows, override_num_blocks=override_num_blocks)


def read_json(paths: str | list, *, lines: bool = True,
              override_num_blocks: int | None = None) -> Dataset:
    import json

    files = _expand(paths)
    rows = []
    for p in files:
        with open(p) as f:
            if lines:
                rows.extend(json.loads(ln) for ln in f if ln.strip())
            else:
                data = json.load(f)
                rows.extend(data if isinstance(data, list) else [data])
    return from_items(rows, override_num_blocks=override_num_blocks)


def read_csv(paths: str | list, *, override_num_blocks: int | None = None
             ) -> Dataset:
    import csv

    files = _expand(paths)
    rows = []
    for p in files:
        with open(p) as f:
            rows.extend(dict(r) for r in csv.DictReader(f))
    return from_items(rows, override_num_blocks=override_num_blocks)


def read_numpy(paths: str | list, *, override_num_blocks: int | None = None
               ) -> Dataset:
    files = _expand(paths)
    rows = []
    for p in files:
        arr = np.load(p)
        rows.extend({"data": a} for a in arr)
    return from_items(rows, override_num_blocks=override_num_blocks)


def read_parquet(paths: str | list, *, override_num_blocks: int | None = None
                 ) -> Dataset:
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover
        raise ImportError("read_parquet requires pyarrow") from e
    files = _expand(paths)
    rows = []
    for p in files:
        rows.extend(pq.read_table(p).to_pylist())
    return from_items(rows, override_num_blocks=override_num_blocks)


def _expand(paths: str | list) -> list:
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        matches = sorted(_glob.glob(p))
        out.extend(matches if matches else [p])
    return out


__all__ = [
    "Dataset", "DataIterator", "GroupedData", "from_items", "range",
    "range_tensor", "from_numpy", "from_pandas", "read_text", "read_json",
    "read_csv", "read_numpy", "read_parquet",
]
